"""Chunked double-buffered EP dispatch (``MoEConfig.a2a_chunks``):
config validation, bit-identity of the chunked pipeline against the
serial schedule (flat / hierarchical / ragged, with and without the
fp8 wire), planner pricing + chunk sweep, measurement keying, the
overlap bound, and the overlap drift monitor."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)
REF = BENCH_CONFIGS["reference"]


@pytest.fixture(autouse=True)
def _hermetic(monkeypatch):
    from flashmoe_tpu import tuning
    from flashmoe_tpu.planner.select import _cached_backend

    for var in ("FLASHMOE_TUNING_FILE", "FLASHMOE_TPU_GEN",
                "FLASHMOE_BENCH_RECORDS", "FLASHMOE_MOCK_SLICES"):
        monkeypatch.delenv(var, raising=False)
    tuning._load.cache_clear()
    _cached_backend.cache_clear()
    yield
    tuning._load.cache_clear()
    _cached_backend.cache_clear()


# ----------------------------------------------------------------------
# Config validation: clear ValueError at config time, not a shape error
# inside the pipeline loop
# ----------------------------------------------------------------------

def test_config_validates_chunk_counts():
    with pytest.raises(ValueError, match="positive int"):
        MoEConfig(a2a_chunks=0, **F32)
    with pytest.raises(ValueError, match="positive int"):
        MoEConfig(a2a_chunks=-2, **F32)
    # E=8, ep=2 -> nLx=4: 3 does not divide
    with pytest.raises(ValueError, match="divide the local-expert"):
        MoEConfig(num_experts=8, ep=2, a2a_chunks=3, **F32)
    # mixtral shape: nLx=1 at ep=8 has no chunk axis
    with pytest.raises(ValueError, match="divide the local-expert"):
        BENCH_CONFIGS["mixtral"].replace(a2a_chunks=2)
    # valid counts construct and stay hashable (jit static args)
    hash(MoEConfig(num_experts=8, ep=2, a2a_chunks=4, **F32))
    hash(MoEConfig(num_experts=8, ep=2, a2a_chunks=1, **F32))


# ----------------------------------------------------------------------
# Bit-identity: chunked on vs off (the a2a_chunks=None guarantee)
# ----------------------------------------------------------------------

def _setup(ep=2, **over):
    base = dict(num_experts=8, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=32 * ep,
                drop_tokens=False, ep=ep, **F32)
    base.update(over)
    cfg = MoEConfig(**base)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    return cfg, params, x


def test_chunked_serial_invariants_via_staticcheck(devices):
    """Serial-schedule identity for the chunk knob across EVERY
    registered EP backend (flat / hierarchical / ragged) — delegated to
    the staticcheck invariant engine, which replaced the hand-rolled
    per-layer assertions this file used to carry: ``a2a_chunks=None``
    is the dataclass default (equal frozen config => one jit cache
    entry => same bits by construction) and ``a2a_chunks=1`` traces to
    the byte-identical jaxpr, while the on-trace's all_to_all count
    scales exactly with the chunk count.  The chunked-ON numeric
    equality against the serial schedule stays execution-tested below
    (slow): a re-ordered schedule being bit-exact is a claim about
    arithmetic, not structure."""
    from flashmoe_tpu.staticcheck.invariants import run_invariants

    assert run_invariants(knobs=["a2a_chunks"], devices=devices,
                          include_coverage=False) == []


@pytest.mark.slow
def test_ep_chunked_bit_identical_hierarchical_and_wire(devices):
    """Chunked + two-stage (intra/inter-slice) exchange + fp8 wire:
    every chunk carries payload AND scales through both hops — outputs
    bit-identical to the serial schedule at the same knobs."""
    cfg, params, x = _setup(ep=4)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    hoff = ep_moe_layer(params, x, cfg, mesh, dcn_inner=2)
    hon = ep_moe_layer(params, x, cfg.replace(a2a_chunks=2), mesh,
                       dcn_inner=2)
    np.testing.assert_array_equal(np.asarray(hoff.out),
                                  np.asarray(hon.out))
    wired = cfg.replace(wire_dtype="e4m3", wire_dtype_combine="e5m2")
    woff = ep_moe_layer(params, x, wired, mesh)
    won = ep_moe_layer(params, x, wired.replace(a2a_chunks=2), mesh)
    np.testing.assert_array_equal(np.asarray(woff.out),
                                  np.asarray(won.out))


@pytest.mark.slow
def test_ragged_chunked_bit_identical(devices):
    """The ragged row exchanges mirror the pipeline: per-chunk
    offsets/sizes derived from the gathered count matrix move exactly
    the serial schedule's rows — with and without the fp8 wire."""
    cfg, params, x = _setup()
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    off = ragged_ep_moe_layer(params, x, cfg, mesh, exchange="dense")
    for n in (2, 4):
        on = ragged_ep_moe_layer(params, x, cfg.replace(a2a_chunks=n),
                                 mesh, exchange="dense")
        np.testing.assert_array_equal(np.asarray(off.out),
                                      np.asarray(on.out))
    wired = cfg.replace(wire_dtype="e4m3")
    woff = ragged_ep_moe_layer(params, x, wired, mesh, exchange="dense")
    won = ragged_ep_moe_layer(params, x, wired.replace(a2a_chunks=2),
                              mesh, exchange="dense")
    np.testing.assert_array_equal(np.asarray(woff.out),
                                  np.asarray(won.out))


@pytest.mark.slow
def test_ep_chunked_grad_finite(devices):
    """Training through the chunked pipeline: grads flow through the
    per-chunk param slices and stay finite."""
    cfg, params, x = _setup(is_training=True, a2a_chunks=2)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])

    def loss(p):
        o = ep_moe_layer(p, x, cfg, mesh)
        return jnp.sum(o.out.astype(jnp.float32) ** 2) + o.aux_loss

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_per_chunk_trace_spans(monkeypatch, devices):
    """Per-chunk phases (moe.a2a_dispatch.k / moe.expert.k /
    moe.a2a_combine.k) wrap the pipeline so xprof and the observe phase
    breakdown see pipeline occupancy.  Trace-only: spans fire at trace
    time, no compile."""
    import contextlib

    from flashmoe_tpu.parallel import ep as ep_mod
    from flashmoe_tpu.parallel import ragged_ep as ragged_mod
    from flashmoe_tpu.utils import telemetry as tel

    seen = []

    @contextlib.contextmanager
    def spy(name):
        seen.append(name)
        yield

    monkeypatch.setattr(ep_mod, "trace_span", spy)
    monkeypatch.setattr(ragged_mod, "trace_span", spy)
    monkeypatch.setattr(tel, "trace_span", spy)
    cfg, params, x = _setup(a2a_chunks=2)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    jax.make_jaxpr(lambda p, xx: ep_moe_layer(p, xx, cfg, mesh))(params, x)
    for k in range(2):
        for phase in ("a2a_dispatch", "expert", "a2a_combine"):
            assert f"moe.{phase}.{k}" in seen, (phase, k, seen)
    seen.clear()
    jax.make_jaxpr(lambda p, xx: ragged_ep_moe_layer(
        p, xx, cfg, mesh, exchange="dense"))(params, x)
    for k in range(2):
        for phase in ("a2a_dispatch", "expert", "a2a_combine"):
            assert f"moe.{phase}.{k}" in seen, (phase, k, seen)


def test_runtime_divisibility_error(devices):
    """A chunk count the ACTUAL mesh cannot divide fails with the clear
    ValueError at trace time, not a shape error inside the loop: a
    cfg.ep=1 config passes the config-time check with any divisor of E,
    but the shard body re-checks against the mesh's real ep width."""
    cfg, params, x = _setup(ep=2, num_experts=8)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    # config-time ok (ep=1 -> nLx=8, 8 divides); mesh nLx=4 does not
    cfg8 = cfg.replace(ep=1, a2a_chunks=8)
    with pytest.raises(ValueError, match="divide the local-expert"):
        jax.make_jaxpr(
            lambda p, xx: ep_moe_layer(p, xx, cfg8, mesh))(params, x)
    with pytest.raises(ValueError, match="divide the local-expert"):
        jax.make_jaxpr(lambda p, xx: ragged_ep_moe_layer(
            p, xx, cfg8, mesh, exchange="dense"))(params, x)


# ----------------------------------------------------------------------
# Planner pricing: chunked-leg costs + overlap-adjusted makespan
# ----------------------------------------------------------------------

def test_chunked_transport_alpha_overhead():
    from flashmoe_tpu.analysis import a2a_transport_cost

    base = a2a_transport_cost(8, 2, 1e6, gen="v5e", links=4)
    ch = a2a_transport_cost(8, 2, 1e6, gen="v5e", links=4, chunks=4)
    # beta unchanged, alpha x4: strictly more expensive per leg ...
    assert ch["flat"]["dcn_ms"] > base["flat"]["dcn_ms"]
    assert ch["flat"]["ici_ms"] > base["flat"]["ici_ms"]
    assert ch["flat"]["dcn_messages"] == 4 * base["flat"]["dcn_messages"]
    with pytest.raises(ValueError, match="chunks"):
        a2a_transport_cost(8, 2, 1e6, chunks=0)


def test_chunked_pipeline_formula():
    from flashmoe_tpu.analysis import chunked_pipeline_ms

    # n=1 is exactly the serial sum
    assert chunked_pipeline_ms(3.0, 1.0, 1.0, 1) == 5.0
    # compute-bound: chip + E/n
    assert chunked_pipeline_ms(4.0, 1.0, 1.0, 2) == pytest.approx(5.0)
    # wire-bound: E + chip/n
    assert chunked_pipeline_ms(1.0, 4.0, 4.0, 2) == pytest.approx(8.5)
    # always <= serial at equal leg costs
    for n in (2, 4, 8):
        assert chunked_pipeline_ms(3.0, 1.0, 1.0, n) < 5.0
    with pytest.raises(ValueError, match="chunks"):
        chunked_pipeline_ms(1.0, 1.0, 1.0, 0)


def test_planner_chunked_beats_serial_on_golden_configs():
    """Acceptance bar: with a2a_chunks >= 2 the overlap-adjusted
    prediction beats the serial prediction on the golden v5e/v5p
    multi-chip configs, for both XLA transports."""
    from flashmoe_tpu.planner.model import predict_paths

    for cname in ("reference", "deepseek"):
        cfg = BENCH_CONFIGS[cname]
        for gen in ("v5e", "v5p"):
            off = {p.path: p for p in predict_paths(cfg, 8, gen)}
            on = {p.path: p for p in predict_paths(
                cfg.replace(a2a_chunks=4), 8, gen)}
            for path in ("collective", "ragged"):
                assert on[path].total_ms < off[path].total_ms, (
                    cname, gen, path)
                # the pipeline pays its alpha overhead visibly ...
                assert on[path].ici_ms > off[path].ici_ms
                # ... and stays below its own no-overlap makespan
                assert on[path].total_ms < on[path].serial_ms
                assert on[path].a2a_chunks == 4
                assert "chunked a2a x4" in on[path].note
            # fused rows ignore the knob: identical pricing, chunks=1
            for path, p in on.items():
                if path.startswith("fused"):
                    assert p.a2a_chunks == 1
                    assert p.total_ms == off[path].total_ms


def test_planner_rejects_indivisible_chunks():
    from flashmoe_tpu.planner.model import predict_paths

    with pytest.raises(ValueError, match="divide the local-expert"):
        # 16 divides E=64 (so the ep=1 config constructs) but not the
        # d=8 local-expert axis E//d = 8
        predict_paths(REF.replace(ep=1, a2a_chunks=16), 8, "v5e")


def test_chunked_composes_with_wire_pricing():
    from flashmoe_tpu.planner.model import predict_paths

    on = {p.path: p for p in predict_paths(
        REF.replace(a2a_chunks=4, wire_dtype="e4m3"), 8, "v5e")}
    both_off = {p.path: p for p in predict_paths(REF, 8, "v5e")}
    assert on["collective"].total_ms < both_off["collective"].total_ms
    assert on["collective"].wire == "e4m3/off"
    assert on["collective"].a2a_chunks == 4
    for pname, p in on.items():
        if pname.startswith("fused"):
            assert not p.feasible  # wire still disqualifies fused


# ----------------------------------------------------------------------
# Selection: the auto chunk sweep + measured override keying
# ----------------------------------------------------------------------

def test_select_sweeps_chunks_and_resolves_plan():
    from flashmoe_tpu.planner.select import (
        resolve_moe_plan, select_path,
    )

    sel = select_path(REF, 8, "v5e", record=False, sweep_chunks=True)
    ns = [n for n, _ in sel.chunk_sweep]
    assert 1 in ns and len(ns) > 1
    assert sel.a2a_chunks > 1  # chunking wins at v5e on this shape
    # the sweep's serial entry matches the unswept selection
    serial = select_path(REF, 8, "v5e", record=False)
    assert dict(sel.chunk_sweep)[1] == pytest.approx(
        serial.predicted_ms, abs=1e-6)
    assert serial.a2a_chunks == 1 and serial.chunk_sweep == ((
        1, round(serial.predicted_ms, 6)),)
    # an explicit cfg.a2a_chunks pins the sweep
    pinned = select_path(REF.replace(a2a_chunks=2), 8, "v5e",
                         record=False, sweep_chunks=True)
    assert [n for n, _ in pinned.chunk_sweep] == [2]
    assert pinned.a2a_chunks == 2
    # auto resolution returns (backend, chunks)
    backend, chunks = resolve_moe_plan(
        REF.replace(moe_backend="auto", ep=8))
    assert backend in ("collective", "ragged", "fused")
    if backend == "fused":
        assert chunks is None
    else:
        assert chunks is None or chunks > 1
    # explicit configs pass through untouched
    assert resolve_moe_plan(
        REF.replace(moe_backend="collective", ep=8, a2a_chunks=2)
    ) == ("collective", 2)


def test_auto_layer_threads_chunk_pick(monkeypatch, devices):
    """auto_ep_moe_layer threads the planner's chunk pick into the
    layer config (trace-only: the chunked graph has 2n all_to_alls)."""
    from flashmoe_tpu.parallel import ep as ep_mod

    cfg, params, x = _setup(ep=2, num_experts=8,
                            moe_backend="auto")
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    monkeypatch.setattr(ep_mod, "resolve_moe_plan",
                        lambda c, m=None: ("collective", 2))
    jx = jax.make_jaxpr(lambda p, xx: ep_mod.auto_ep_moe_layer(
        p, xx, cfg, mesh))(params, x)
    n_a2a = str(jx).count("all_to_all")
    assert n_a2a == 4  # 2 legs x 2 chunks


def test_measured_override_keyed_by_chunks(tmp_path, monkeypatch):
    """A path latency measured at chunks=4 never overrides a serial
    selection (and vice versa) — tuning table and bench records."""
    from flashmoe_tpu import tuning
    from flashmoe_tpu.planner.select import (
        _bench_record_latencies, _cached_backend, select_path,
    )

    shape = dict(h=REF.hidden_size, i=REF.intermediate_size, d=8)
    tbl = tmp_path / "table.json"
    tbl.write_text(json.dumps({"generation": "v5e", "entries": [
        {"kernel": "path_latency",
         "match": dict(shape, path="ragged", chunks=4),
         "measured_ms": 0.0001},
        {"kernel": "path_latency",          # legacy: implicit serial
         "match": dict(shape, path="collective"),
         "measured_ms": 0.0002},
    ]}))
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(tbl))
    tuning._load.cache_clear()
    _cached_backend.cache_clear()
    # serial query: only the legacy entry applies
    assert tuning.measured_path_latencies(
        "v5e", **shape) == {"collective": 0.0002}
    # chunked query: only the chunks=4 entry applies
    assert tuning.measured_path_latencies(
        "v5e", **shape, chunks=4) == {"ragged": 0.0001}
    # through the sweep: the chunks=4 measurement wins overall and
    # carries its chunk identity into the selection
    sel = select_path(REF, 8, "v5e", record=False, sweep_chunks=True)
    assert (sel.mode, sel.winner) == ("measured", "ragged")
    assert sel.a2a_chunks == 4 and sel.measured_ms == 0.0001

    # bench records: a2a_chunks field keys the same way
    metric = (f"moe_layer_fwd_ms[x:E={REF.num_experts},"
              f"k={REF.expert_top_k},H={REF.hidden_size},"
              f"I={REF.intermediate_size},S={REF.tokens},bfloat16]")
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(
        {"metric": metric, "path": "collective", "value": 0.5, "d": 8,
         "a2a_chunks": 4}) + "\n" + json.dumps(
        {"metric": metric, "path": "ragged", "value": 0.7, "d": 8}) + "\n")
    monkeypatch.setenv("FLASHMOE_BENCH_RECORDS", str(p))
    assert _bench_record_latencies(REF, 8) == {"ragged": 0.7}
    assert _bench_record_latencies(
        REF.replace(a2a_chunks=4), 8) == {"collective": 0.5}
    assert _bench_record_latencies(
        REF.replace(a2a_chunks=2), 8) == {}


# ----------------------------------------------------------------------
# Overlap bound + drift monitor
# ----------------------------------------------------------------------

def test_chunked_overlap_bound_pieces():
    from flashmoe_tpu.parallel.overlap import chunked_overlap_bound

    serial = chunked_overlap_bound(REF, 8, "v5e", 1)
    assert serial["overlap_efficiency_bound"] == pytest.approx(1.0)
    b4 = chunked_overlap_bound(REF, 8, "v5e", 4)
    assert b4["overlap_efficiency_bound"] > 1.0
    # the bound mirrors the operational metric: (C + E) / T
    assert b4["overlap_efficiency_bound"] == pytest.approx(
        b4["serial_ms"] / b4["t_overlapped_ms"])
    # upper bound shape: never above (a+b)/max(a,b)
    a = b4["compute_ms"]
    e = b4["leg_dispatch_ms"] + b4["leg_combine_ms"]
    assert b4["overlap_efficiency_bound"] <= (a + e) / max(a, e) + 1e-9
    # ragged slabs are smaller at cf>1 configs; both paths priced
    rag = chunked_overlap_bound(BENCH_CONFIGS["deepseek"], 8, "v5e", 4,
                                path="ragged")
    assert rag["path"] == "ragged" and rag["t_overlapped_ms"] > 0
    with pytest.raises(ValueError):
        chunked_overlap_bound(REF, 8, "v7x", 2)
    with pytest.raises(ValueError, match="chunks"):
        chunked_overlap_bound(REF, 8, "v5e", 0)
    with pytest.raises(ValueError, match="fused"):
        chunked_overlap_bound(REF, 8, "v5e", 2, path="fused")


def test_overlap_drift_record_and_warning():
    from flashmoe_tpu.planner.drift import record_overlap_drift
    from flashmoe_tpu.utils.telemetry import metrics

    rec = record_overlap_drift(
        "collective", 1.30, predicted_fraction=1.40, gen="v5e", d=8,
        chunks=4)
    assert not rec.exceeded
    assert rec.rel_error == pytest.approx(1.30 / 1.40 - 1.0)
    d = metrics.last_decision("planner.overlap_drift")
    assert d["chunks"] == 4 and d["path"] == "collective"
    with pytest.warns(RuntimeWarning, match="overlap-fraction drift"):
        bad = record_overlap_drift(
            "collective", 0.5, predicted_fraction=1.8, gen="v5e", d=8,
            chunks=4)
    assert bad.exceeded
    with pytest.raises(ValueError, match="predicted_fraction"):
        record_overlap_drift("collective", 1.0,
                             predicted_fraction=0.0, gen="v5e", d=8)


@pytest.mark.slow
def test_measure_overlap_ragged_arm_and_chunk_passthrough(devices):
    """The ragged overlap arm runs end to end on the virtual mesh and
    the a2a_chunks passthrough reaches the overlapped leg; the fused
    arm refuses the knob."""
    from flashmoe_tpu.parallel.overlap import measure_overlap

    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=64,
                    capacity_factor=1.0, drop_tokens=True, ep=2, **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    m = measure_overlap(cfg, mesh, path="ragged", trials=1, chain=2,
                        a2a_chunks=2)
    assert m["path"] == "ragged" and m["a2a_chunks"] == 2
    assert m["t_overlapped_ms"] > 0 and m["overlap_efficiency"] > 0
    with pytest.raises(ValueError, match="fused"):
        measure_overlap(cfg, mesh, path="fused", a2a_chunks=2)
    with pytest.raises(ValueError, match="unknown path"):
        measure_overlap(cfg, mesh, path="sideways")
