"""Tier-1 budget guard: collection-time marker hygiene.

The fast gate (``pytest -m 'not slow'``) must stay inside its 870s
budget (ROADMAP.md).  The expensive test classes — end-to-end chaos
drills (full training jobs per fault) and multi-device shard_map
*executions* (trace-only jaxpr inspection is cheap; running the
collectives is not) — are required to carry ``@pytest.mark.slow`` so a
new drill can never silently land in the fast lane.  AST-based: no
pytest-in-pytest, no imports of the heavy modules.
"""

import ast
import os

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))

#: calls that make a test a chaos DRILL (a full resilient training job)
DRILL_CALLS = {"run_drill", "run_matrix"}

#: calls that EXECUTE a shard_map'd MoE layer on the virtual mesh
#: (jax.make_jaxpr over the same layer is trace-only and stays fast)
SHARD_MAP_CALLS = {"ep_moe_layer", "ragged_ep_moe_layer",
                   "fused_ep_moe_layer"}


def _called_names(node: ast.AST) -> set:
    names = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name):
                names.add(f.id)
            elif isinstance(f, ast.Attribute):
                names.add(f.attr)
    return names


def _is_slow_marked(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        text = ast.unparse(dec)
        if "mark.slow" in text:
            return True
    return False


def _test_functions(path: str):
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if isinstance(node, ast.FunctionDef) and \
                node.name.startswith("test_"):
            yield node


def test_every_chaos_drill_test_is_slow_marked():
    """Any test in any file that runs a chaos drill must be slow: one
    drill is a whole resilient training job (compile + steps + restore),
    ~5-10s each on CPU."""
    offenders = []
    for name in sorted(os.listdir(TESTS_DIR)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        for fn in _test_functions(os.path.join(TESTS_DIR, name)):
            if _called_names(fn) & DRILL_CALLS and not _is_slow_marked(fn):
                offenders.append(f"{name}::{fn.name}")
    assert not offenders, (
        f"chaos drill tests missing @pytest.mark.slow: {offenders} — "
        f"drills are full training jobs and belong outside the fast "
        f"gate (ROADMAP.md tier-1 budget)")


def test_chaos_shard_map_executions_are_slow_marked():
    """test_chaos.py may TRACE the ep layers cheaply (jax.make_jaxpr)
    but must not EXECUTE them in the fast lane."""
    offenders = []
    path = os.path.join(TESTS_DIR, "test_chaos.py")
    for fn in _test_functions(path):
        called = _called_names(fn)
        if called & SHARD_MAP_CALLS and "make_jaxpr" not in called \
                and not _is_slow_marked(fn):
            offenders.append(fn.name)
    assert not offenders, (
        f"test_chaos.py tests executing shard_map layers without "
        f"@pytest.mark.slow: {offenders}")


def test_collection_guard_sees_the_known_slow_tests():
    """Self-check: the AST scan actually finds the known drill/execution
    tests — an empty scan would make the guards vacuously green."""
    path = os.path.join(TESTS_DIR, "test_chaos.py")
    drills = [fn.name for fn in _test_functions(path)
              if _called_names(fn) & DRILL_CALLS]
    execs = [fn.name for fn in _test_functions(path)
             if _called_names(fn) & SHARD_MAP_CALLS
             and "make_jaxpr" not in _called_names(fn)]
    assert "test_drill_matrix" in drills
    assert "test_drill_preempt_drains_with_zero_lost_steps" in drills
    assert "test_degrade_ep_layer_masks_and_counts" in execs
