"""Tier-1 budget guard: collection-time marker hygiene.

The fast gate (``pytest -m 'not slow'``) must stay inside its 870s
budget (ROADMAP.md).  The expensive test classes — end-to-end chaos
drills (full training jobs per fault) and multi-device shard_map
*executions* (trace-only jaxpr inspection is cheap; running the
collectives is not) — are required to carry ``@pytest.mark.slow`` so a
new drill can never silently land in the fast lane.

The AST rule itself lives in the static-analysis subsystem
(``flashmoe_tpu/staticcheck/lint.py`` — where ``python -m
flashmoe_tpu.staticcheck --lint`` runs it alongside the other rules);
this file is the thin tier-1 wrapper that keeps the historical gate
names and coverage."""

from flashmoe_tpu.staticcheck.lint import (
    DRILL_CALLS, SHARD_MAP_CALLS, check_slow_marks, slow_mark_selfcheck,
)

assert DRILL_CALLS and SHARD_MAP_CALLS  # engine still exports the rule


def test_every_chaos_drill_test_is_slow_marked():
    """Any test in any file that runs a chaos drill must be slow: one
    drill is a whole resilient training job (compile + steps + restore),
    ~5-10s each on CPU."""
    offenders = [str(v) for v in check_slow_marks()
                 if "chaos drill" in v.detail]
    assert not offenders, offenders


def test_chaos_shard_map_executions_are_slow_marked():
    """test_chaos.py may TRACE the ep layers cheaply (jax.make_jaxpr)
    but must not EXECUTE them in the fast lane."""
    offenders = [str(v) for v in check_slow_marks()
                 if "shard_map" in v.detail]
    assert not offenders, offenders


def test_collection_guard_sees_the_known_slow_tests():
    """Self-check: the AST scan actually finds the known drill/execution
    tests — an empty scan would make the guards vacuously green."""
    assert slow_mark_selfcheck() == []
