import json

import jax.numpy as jnp
import pytest

from flashmoe_tpu.config import BENCH_CONFIGS, Activation, MoEConfig


def test_defaults_derive():
    cfg = MoEConfig()
    assert cfg.tokens == 128
    assert cfg.num_local_experts == 8
    assert cfg.padded_num_experts == 128
    # EC = ceil(1.25 * 2 * ceil(128/8)) = 40
    assert cfg.expert_capacity == 40
    assert cfg.padded_expert_capacity % 8 == 0


def test_no_drop_capacity_is_all_tokens():
    cfg = MoEConfig(drop_tokens=False, sequence_len=256)
    assert cfg.expert_capacity == 256


def test_validation():
    with pytest.raises(ValueError):
        MoEConfig(hidden_size=100)
    with pytest.raises(ValueError):
        MoEConfig(expert_top_k=9, num_experts=8)
    with pytest.raises(ValueError):
        MoEConfig(num_experts=6, ep=4)


def test_from_reference_json():
    # mirror of csrc/flashmoe_config.json
    raw = {
        "capacity_factor": 1, "drop_tokens": 1, "expert_top_k": 2,
        "global_batch": 1, "is_training": 0, "hidden_act": 0,
        "hidden_size": 2048, "intermediate_size": 2048, "mini_batch": 1,
        "moe_frequency": 2, "num_experts": 64, "num_layers": 2,
        "sequence_len": 8192, "torch_dtype": 1, "vocab_size": 50257,
    }
    cfg = MoEConfig.from_json(raw)
    assert cfg.num_experts == 64
    assert cfg.hidden_act == Activation.RELU
    assert cfg.dtype == jnp.bfloat16
    assert cfg.tokens == 8192
    # EC = 1 * 2 * ceil(8192/64) = 256
    assert cfg.expert_capacity == 256
    json.loads(cfg.to_json())


def test_moe_layer_indices():
    cfg = MoEConfig(num_layers=4, moe_frequency=2)
    assert cfg.moe_layer_indices == (1, 3)
    dense = MoEConfig(num_experts=1, expert_top_k=1)
    assert dense.moe_layer_indices == ()


def test_bench_configs_valid():
    for name, cfg in BENCH_CONFIGS.items():
        assert cfg.tokens > 0, name
        assert cfg.expert_capacity > 0, name
