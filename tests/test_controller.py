"""Self-healing runtime controller (flashmoe_tpu/runtime/controller.py):
trigger dynamics, action planning, live-state re-placement, replica
routing, drift-corrected replan, and manifest persistence.

The end-to-end chaos proofs (sustained skew must morph, a slow device
must re-place, through a real resilient training job) live in the
slow-marked drills of tests/test_chaos.py; this file covers the
controller's host-side machinery fast."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.planner import adapt
from flashmoe_tpu.runtime.controller import (
    ControllerConfig, MorphAction, ReplaceAction, RuntimeController,
    permute_expert_state,
)
from flashmoe_tpu.utils.telemetry import Metrics


def _cfg(**over):
    base = dict(num_experts=8, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=64,
                dtype=jnp.float32, param_dtype=jnp.float32,
                collect_stats=True, is_training=True)
    base.update(over)
    return MoEConfig(**base)


def _stats(load, dropped=0.0):
    load = np.asarray(load, dtype=np.float64)
    mean = max(float(load.mean()), 1e-9)
    return {"expert_load": load.tolist(),
            "dropped_fraction": float(dropped),
            "imbalance": float(load.max()) / mean}


def _ctrl(cfg=None, ccfg=None, **kw):
    m = Metrics()
    c = RuntimeController(cfg or _cfg(), ccfg or ControllerConfig(
        debounce_steps=2, cooldown_steps=4, baseline_steps=2,
        ema_decay=0.5), metrics=m, **kw)
    return c, m


# ----------------------------------------------------------------------
# Config validation
# ----------------------------------------------------------------------

def test_controller_config_validation():
    with pytest.raises(ValueError, match="debounce"):
        ControllerConfig(debounce_steps=0)
    with pytest.raises(ValueError, match="ema_decay"):
        ControllerConfig(ema_decay=1.5)
    with pytest.raises(ValueError, match="slow_factor"):
        ControllerConfig(slow_factor=0.9)


def test_expert_replicas_config_validation():
    with pytest.raises(ValueError, match="own slot"):
        _cfg(expert_replicas=((2, 2),))
    with pytest.raises(ValueError, match="out of range"):
        _cfg(expert_replicas=((0, 9),))
    with pytest.raises(ValueError, match="twice"):
        _cfg(expert_replicas=((0, 3), (1, 3)))
    with pytest.raises(ValueError, match="chains"):
        _cfg(expert_replicas=((0, 1), (1, 2)))
    with pytest.raises(ValueError, match="exactly one replica"):
        # the parity split supports one replica per hot expert; a
        # second pair for the same expert would get zero traffic
        _cfg(expert_replicas=((0, 1), (0, 2)))
    with pytest.raises(ValueError, match="int pairs"):
        _cfg(expert_replicas=((0,),))
    assert _cfg(expert_replicas=((0, 3), (1, 4))).expert_replicas


# ----------------------------------------------------------------------
# Trigger dynamics: debounce, hysteresis, cooldown, budgets
# ----------------------------------------------------------------------

def test_skew_trigger_debounces_and_resets_on_clear():
    c, _ = _ctrl()
    skewed = {"moe_stats": [_stats([60, 1, 1, 1, 1, 1, 1, 1], 0.3)]}
    calm = {"moe_stats": [_stats(np.ones(8), 0.0)]}
    c.observe_step(0, 10.0, skewed)
    assert c._skew_run == 1
    assert c.maybe_act(1) is None          # below the debounce window
    # hysteresis: a clear observation resets the run; the EMA is decayed
    # far enough by repeated calm steps that the condition truly clears
    for s in range(1, 6):
        c.observe_step(s, 10.0, calm)
    assert c._skew_run == 0
    assert c.maybe_act(6) is None


def test_one_step_blip_never_triggers():
    c, m = _ctrl()
    calm = {"moe_stats": [_stats(np.ones(8), 0.0)]}
    blip = {"moe_stats": [_stats([60, 1, 1, 1, 1, 1, 1, 1], 0.5)]}
    for s in range(4):
        c.observe_step(s, 10.0, calm)
    c.observe_step(4, 10.0, blip)
    for s in range(5, 12):
        c.observe_step(s, 10.0, calm)
        assert c.maybe_act(s + 1) is None
    assert c.morphs_used == 0 and c.replaces_used == 0
    assert not [d for d in m.decisions
                if d["decision"].startswith("controller.")]


def test_morph_fires_after_debounce_and_respects_budget_and_cooldown():
    c, m = _ctrl(ccfg=ControllerConfig(
        debounce_steps=2, cooldown_steps=4, baseline_steps=2,
        ema_decay=0.5, morph_budget=1, enable_replace=False))
    skewed = {"moe_stats": [_stats([60, 1, 1, 1, 1, 1, 1, 1], 0.3)]}
    c.observe_step(0, 10.0, skewed)
    c.observe_step(1, 10.0, skewed)
    act = c.maybe_act(2)
    assert isinstance(act, MorphAction) and act.needs_rebuild
    assert act.overrides == {"drop_tokens": False}
    assert c.cfg_overrides == {"drop_tokens": False}
    rec = m.last_decision("controller.morph")
    assert rec is not None and rec["dropless"] and rec["trigger"] == "skew"
    # cooldown: triggers inside the window are recorded, not acted on
    c.observe_step(2, 10.0, skewed)
    c.observe_step(3, 10.0, skewed)
    assert c.maybe_act(4) is None
    cd = m.last_decision("controller.cooldown")
    assert cd is not None and cd["trigger"] == "skew"
    # budget spent: even past the cooldown no second morph fires
    for s in range(4, 12):
        c.observe_step(s, 10.0, skewed)
    assert c.maybe_act(12) is None
    assert c.morphs_used == 1


def test_morph_requires_rebuild_capability():
    c, m = _ctrl(ccfg=ControllerConfig(
        debounce_steps=1, cooldown_steps=2, baseline_steps=2,
        ema_decay=0.5, enable_replace=False))
    skewed = {"moe_stats": [_stats([60, 1, 1, 1, 1, 1, 1, 1], 0.3)]}
    c.observe_step(0, 10.0, skewed)
    assert c.maybe_act(1, can_rebuild=False) is None
    assert c.morphs_used == 0


def test_slow_trigger_plans_replacement_with_rates():
    rates = np.array([0.25, 1.0, 1.0, 1.0])
    c, m = _ctrl(cfg=_cfg(expert_top_k=1),
                 ccfg=ControllerConfig(
                     debounce_steps=2, cooldown_steps=4,
                     baseline_steps=2, ema_decay=0.5,
                     enable_morph=False),
                 n_devices=4, rates_fn=lambda: rates)
    hot = {"moe_stats": [_stats([64, 0, 0, 0, 0, 0, 0, 0])]}
    c.observe_step(0, 10.0, hot)    # baseline (fast)
    c.observe_step(1, 10.0, hot)
    c.observe_step(2, 900.0, hot)   # the device degrades
    c.observe_step(3, 900.0, hot)
    act = c.maybe_act(4)
    assert isinstance(act, ReplaceAction)
    assert sorted(act.perm) == list(range(8))
    assert act.perm != tuple(range(8))
    # hot expert leaves the slow device (slots 0..1)
    new_hot = act.perm.index(0)
    assert new_hot // 2 != 0
    # a dead slot carries the replica, on another device
    assert act.replica_pairs
    h, v = act.replica_pairs[0]
    assert h == new_hot and v // 2 != new_hot // 2
    assert act.overrides["expert_replicas"] == act.replica_pairs
    rec = m.last_decision("controller.replace")
    assert rec["rates"] == rates.tolist()
    assert rec["trigger"] == "slow"


def test_default_rates_fn_is_live_probe_with_chaos_seam():
    """ISSUE 12 satellite: a controller constructed WITHOUT rates_fn
    must re-probe per-device throughput on the slow trigger
    (runtime/throughput.device_rates).  The probe_rates chaos seam
    supplies the degraded reading (what a genuinely slow chip would
    hand the probe), and the resulting re-placement must consume it —
    the decision record carries the probed vector."""
    from flashmoe_tpu.chaos import inject
    from flashmoe_tpu.runtime import throughput

    inject.arm("probe_rates", rates=(0.25, 1.0, 1.0, 1.0))
    try:
        # the seam short-circuits before any backend work
        rates = throughput.device_rates(_cfg(), 4)
        assert list(rates) == [0.25, 1.0, 1.0, 1.0]
        c, m = _ctrl(cfg=_cfg(expert_top_k=1),
                     ccfg=ControllerConfig(
                         debounce_steps=2, cooldown_steps=4,
                         baseline_steps=2, ema_decay=0.5,
                         enable_morph=False),
                     n_devices=4)          # NO rates_fn: default probe
        hot = {"moe_stats": [_stats([64, 0, 0, 0, 0, 0, 0, 0])]}
        c.observe_step(0, 10.0, hot)
        c.observe_step(1, 10.0, hot)
        c.observe_step(2, 900.0, hot)
        c.observe_step(3, 900.0, hot)
        act = c.maybe_act(4)
        assert isinstance(act, ReplaceAction)
        # hot expert leaves the probed-slow device (slots 0..1)
        assert act.perm.index(0) // 2 != 0
        rec = m.last_decision("controller.replace")
        assert rec["rates"] == [0.25, 1.0, 1.0, 1.0]
    finally:
        inject.disarm("probe_rates")


def test_probe_failure_degrades_to_uniform_rates(monkeypatch):
    """A raising probe must never block the step boundary: re-placement
    degrades to uniform rates and records controller.probe_error."""
    from flashmoe_tpu.runtime import throughput

    def boom(*a, **kw):
        raise RuntimeError("wedged tunnel")

    monkeypatch.setattr(throughput, "device_rates", boom)
    c, m = _ctrl(cfg=_cfg(expert_top_k=1),
                 ccfg=ControllerConfig(
                     debounce_steps=2, cooldown_steps=4,
                     baseline_steps=2, ema_decay=0.5,
                     enable_morph=False),
                 n_devices=4)
    hot = {"moe_stats": [_stats([64, 0, 0, 0, 0, 0, 0, 0])]}
    c.observe_step(0, 10.0, hot)
    c.observe_step(1, 10.0, hot)
    c.observe_step(2, 900.0, hot)
    c.observe_step(3, 900.0, hot)
    act = c.maybe_act(4)
    assert isinstance(act, ReplaceAction)  # uniform-rate rebalance
    err = m.last_decision("controller.probe_error")
    assert err is not None and "wedged" in err["reason"]
    assert m.last_decision("controller.replace")["rates"] is None


def test_replace_noop_when_layout_already_balanced():
    c, m = _ctrl(ccfg=ControllerConfig(
        debounce_steps=2, cooldown_steps=4, baseline_steps=2,
        ema_decay=0.5, enable_morph=False), n_devices=4)
    balanced = {"moe_stats": [_stats(np.ones(8))]}
    c.observe_step(0, 10.0, balanced)
    c.observe_step(1, 10.0, balanced)
    c.observe_step(2, 900.0, balanced)  # slow, but nothing to re-place
    c.observe_step(3, 900.0, balanced)
    assert c.maybe_act(4) is None
    assert c.replaces_used == 0
    cd = m.last_decision("controller.cooldown")
    assert cd is not None and "noop" in cd["reason"]


def test_action_resets_baseline_for_the_new_regime():
    c, _ = _ctrl(ccfg=ControllerConfig(
        debounce_steps=1, cooldown_steps=3, baseline_steps=2,
        ema_decay=0.5, enable_replace=False))
    skewed = {"moe_stats": [_stats([60, 1, 1, 1, 1, 1, 1, 1], 0.3)]}
    c.observe_step(0, 10.0, skewed)
    assert isinstance(c.maybe_act(1), MorphAction)
    assert c.baseline_ms is None and c.step_ms_ema is None


# ----------------------------------------------------------------------
# Persistence: state_dict round trip + monotonic budgets
# ----------------------------------------------------------------------

def test_state_dict_roundtrip_and_monotonic_budgets():
    c, _ = _ctrl()
    c.overrides = {"drop_tokens": False,
                   "expert_replicas": ((2, 5),)}
    c.morphs_used, c.replaces_used = 1, 2
    sd = c.state_dict()
    import json

    json.dumps(sd)  # manifest-ready
    c2, _ = _ctrl()
    c2.load_state_dict(sd)
    assert c2.cfg_overrides == c.overrides
    assert isinstance(c2.overrides["expert_replicas"], tuple)
    # budgets never refill on a rewind to an older manifest
    c2.morphs_used = 5
    c2.load_state_dict(sd)
    assert c2.morphs_used == 5 and c2.replaces_used == 2
    # a manifest without replicas clears the replica map
    c2.load_state_dict({"overrides": {"drop_tokens": False}})
    assert "expert_replicas" not in c2.cfg_overrides


def test_manifest_carries_controller_state(tmp_path, devices):
    from flashmoe_tpu.runtime import checkpoint as ckpt
    from flashmoe_tpu.runtime.trainer import init_state, make_optimizer

    cfg = _cfg(num_layers=1, vocab_size=256, num_heads=2)
    opt = make_optimizer(cfg, total_steps=4)
    state = init_state(jax.random.PRNGKey(0), cfg, opt)
    d = str(tmp_path / "ckpt")
    cs = {"overrides": {"drop_tokens": False}, "morphs_used": 1,
          "replaces_used": 0, "timeline": []}
    ckpt.save(d, state, step=2, controller_state=cs)
    assert ckpt.load_controller_state(d, 2) == cs
    # legacy manifests answer None, not an error
    ckpt.save(d, state, step=3)
    assert ckpt.load_controller_state(d, 3) is None


# ----------------------------------------------------------------------
# Live-state re-placement + replica routing
# ----------------------------------------------------------------------

def test_permute_expert_state_preserves_function():
    from flashmoe_tpu.models import transformer
    from flashmoe_tpu.runtime.trainer import init_state, make_optimizer

    cfg = _cfg(num_layers=1, vocab_size=256, num_heads=2,
               collect_stats=False, drop_tokens=False)
    opt = make_optimizer(cfg, total_steps=4)
    state = init_state(jax.random.PRNGKey(0), cfg, opt)
    toks = jax.random.randint(jax.random.PRNGKey(1),
                              (2, cfg.sequence_len), 0, 256)
    base, _ = transformer.forward(state.params, toks, cfg)
    perm = (3, 1, 0, 2, 7, 6, 5, 4)
    st2 = permute_expert_state(state, cfg, perm)
    out, _ = transformer.forward(st2.params, toks, cfg)
    # identical function; numerics equivalent up to router-softmax
    # reassociation (the expert-axis sums reorder)
    np.testing.assert_allclose(np.asarray(base), np.asarray(out),
                               rtol=2e-5, atol=2e-5)
    # params AND their optimizer moments moved together
    w = np.asarray(state.params["layers"][0]["moe"]["w_up"])
    w2 = np.asarray(st2.params["layers"][0]["moe"]["w_up"])
    np.testing.assert_array_equal(w2, w[list(perm)])
    gw = np.asarray(state.params["layers"][0]["moe"]["gate_w"])
    gw2 = np.asarray(st2.params["layers"][0]["moe"]["gate_w"])
    np.testing.assert_array_equal(gw2, gw[:, list(perm)])
    mus = [x for x in jax.tree_util.tree_leaves(state.opt_state)
           if getattr(x, "shape", None) == w.shape]
    mus2 = [x for x in jax.tree_util.tree_leaves(st2.opt_state)
            if getattr(x, "shape", None) == w.shape]
    assert mus and len(mus) == len(mus2)
    for a, b in zip(mus, mus2):
        np.testing.assert_array_equal(np.asarray(b),
                                      np.asarray(a)[list(perm)])


def test_permute_rejects_non_permutation():
    from flashmoe_tpu.runtime.trainer import init_state, make_optimizer

    cfg = _cfg(num_layers=1, vocab_size=256, num_heads=2)
    state = init_state(jax.random.PRNGKey(0), cfg,
                       make_optimizer(cfg, total_steps=4))
    with pytest.raises(ValueError, match="permutation"):
        permute_expert_state(state, cfg, (0, 0, 1, 2, 3, 4, 5, 6))


def test_replica_routing_splits_hot_and_preserves_hot_tokens():
    """With the victim's FFN weights overwritten by the hot expert's
    copy, every token routed to the hot expert computes bit-identically
    (one value-identical replica processes it), and the physical load
    histogram shows the split."""
    from flashmoe_tpu.models.reference import init_moe_params
    from flashmoe_tpu.ops.gate import router
    from flashmoe_tpu.ops.moe import moe_layer

    cfg = _cfg(drop_tokens=False, collect_stats=True)
    p = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    r = router(x, jnp.asarray(p["gate_w"], jnp.float32), cfg,
               use_pallas=False)
    hot = int(np.bincount(
        np.asarray(r.expert_idx).ravel(), minlength=8).argmax())
    victim = int(np.bincount(
        np.asarray(r.expert_idx).ravel(), minlength=8).argmin())
    base = moe_layer(p, x, cfg, use_pallas=False)

    p2 = dict(p)
    for k in ("w_up", "b_up", "w_down", "b_down"):
        arr = np.asarray(p[k]).copy()
        arr[victim] = arr[hot]
        p2[k] = jnp.asarray(arr)
    cfg_r = cfg.replace(expert_replicas=((hot, victim),))
    rep = moe_layer(p2, x, cfg_r, use_pallas=False)

    # tokens that never touched the victim expert are bit-identical
    touched = np.any(np.asarray(r.expert_idx) == victim, axis=1)
    np.testing.assert_array_equal(np.asarray(base.out)[~touched],
                                  np.asarray(rep.out)[~touched])
    # the hot slot's physical load split across the replica pair
    load_b = np.asarray(base.stats.expert_load)
    load_r = np.asarray(rep.stats.expert_load)
    assert load_r[hot] < load_b[hot]
    assert load_r[victim] > load_b[victim]
    assert load_r.sum() == load_b.sum()


def test_replicas_off_is_default_and_router_untouched():
    from flashmoe_tpu.models.reference import init_moe_params
    from flashmoe_tpu.ops.gate import apply_replicas, router

    cfg = _cfg()
    assert cfg.expert_replicas == ()
    p = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    r = router(x, jnp.asarray(p["gate_w"], jnp.float32), cfg,
               use_pallas=False)
    assert apply_replicas(r, cfg) is r


# ----------------------------------------------------------------------
# Drift-corrected replan (planner/adapt.py)
# ----------------------------------------------------------------------

def test_replan_single_chip_dropless_flip():
    plan = adapt.replan(_cfg(), 1, prefer_dropless=True)
    assert plan.overrides == {"drop_tokens": False}
    assert plan.dropless and plan.mode == "dropless"
    # already dropless: nothing to do
    plan2 = adapt.replan(_cfg(drop_tokens=False), 1,
                         prefer_dropless=True)
    assert plan2.is_noop


def test_replan_prefers_ragged_for_drop_trigger_at_width():
    cfg = _cfg(num_experts=16, ep=8, sequence_len=128)
    plan = adapt.replan(cfg, 8, gen="v5e", prefer_dropless=True)
    assert plan.dropless
    assert plan.overrides.get("drop_tokens") is False
    if plan.backend == "ragged":
        assert plan.overrides.get("moe_backend") == "ragged"


def test_replan_measured_ledger_demotes_slow_path():
    """A measured cost far above every alternative MUST move the
    selection off the running path — the measurement corrects the
    running family's prior and then competes against the other
    families' priors (select_path's measured-winner rule would instead
    re-elect the only-measured degraded path: the bug this pins)."""
    cfg = _cfg(num_experts=16, ep=8, sequence_len=128,
               moe_backend="collective")
    fam = adapt.current_family(cfg, 8)
    assert fam == "collective"
    plan = adapt.replan(cfg, 8, gen="v5e",
                        measured_ms=adapt.measured_ledger(fam, 1e6))
    assert plan.mode == "reselect"
    assert plan.backend != "collective"
    assert plan.overrides.get("moe_backend") == plan.backend
    assert plan.predicted_ms < 1e6
    # a healthy measurement re-elects the running path (noop)
    plan2 = adapt.replan(cfg, 8, gen="v5e",
                         measured_ms=adapt.measured_ledger(fam, 1e-6))
    assert plan2.is_noop


# ----------------------------------------------------------------------
# DCN wire morph (ISSUE 13: phase-ledger a2a dominance -> wire_dtype_dcn)
# ----------------------------------------------------------------------

_A2A_HEAVY = {"phase_ms": {"moe.gate": 1.0, "moe.a2a_dispatch": 5.0,
                           "moe.expert": 2.0, "moe.a2a_combine": 4.0,
                           "moe.combine": 0.5}}
_A2A_LIGHT = {"phase_ms": {"moe.gate": 1.0, "moe.a2a_dispatch": 0.5,
                           "moe.expert": 9.0, "moe.a2a_combine": 0.5,
                           "moe.combine": 0.5}}


def test_wire_morph_fires_on_sustained_a2a_dominance():
    c, m = _ctrl(ccfg=ControllerConfig(
        debounce_steps=2, cooldown_steps=4, baseline_steps=2,
        ema_decay=0.5, enable_morph=False, enable_replace=False),
        slices=2)
    c.observe_step(0, 10.0, _A2A_HEAVY)
    assert c._a2a_run == 1
    assert c.maybe_act(1) is None          # below the debounce window
    c.observe_step(1, 10.0, _A2A_HEAVY)
    act = c.maybe_act(2)
    assert isinstance(act, MorphAction) and act.needs_rebuild
    assert act.overrides == {"wire_dtype_dcn": "e4m3"}
    assert act.trigger == "a2a"
    assert c.cfg_overrides == {"wire_dtype_dcn": "e4m3"}
    rec = m.last_decision("controller.wire_morph")
    assert rec is not None and rec["trigger"] == "a2a"
    assert rec["a2a_share_ema"] is not None
    # the morphed config actually constructs (runner rebuild path)
    assert c.apply_to(c.cfg).wire_dtype_dcn == "e4m3"
    # knob now on: the trigger can never re-arm (no oscillation), and
    # the budget is spent regardless
    for s in range(2, 20):
        c.observe_step(s, 10.0, _A2A_HEAVY)
    assert c._a2a_run == 0
    assert c.maybe_act(20) is None
    assert c.wire_morphs_used == 1


def test_wire_morph_needs_multislice_and_resets_on_clear():
    # single-slice job: the signal may spike but the morph never arms
    c, m = _ctrl(ccfg=ControllerConfig(debounce_steps=1,
                                       enable_morph=False,
                                       enable_replace=False))
    c.observe_step(0, 10.0, _A2A_HEAVY)
    assert c._a2a_run == 0 and c.maybe_act(1) is None
    assert not [d for d in m.decisions
                if d["decision"] == "controller.wire_morph"]
    # multi-slice: hysteresis — a clear observation resets the run
    c2, _ = _ctrl(ccfg=ControllerConfig(
        debounce_steps=3, enable_morph=False, enable_replace=False),
        slices=4)
    c2.observe_step(0, 10.0, _A2A_HEAVY)
    c2.observe_step(1, 10.0, _A2A_HEAVY)
    c2.observe_step(2, 10.0, _A2A_LIGHT)
    assert c2._a2a_run == 0


def test_wire_morph_respects_cooldown_and_persists():
    c, m = _ctrl(ccfg=ControllerConfig(
        debounce_steps=1, cooldown_steps=6, baseline_steps=2,
        ema_decay=0.5, enable_morph=False, enable_replace=False,
        wire_morph_dtype="bf16", wire_morph_budget=2), slices=2)
    c.observe_step(0, 10.0, _A2A_HEAVY)
    act = c.maybe_act(1)
    assert act is not None
    assert act.overrides == {"wire_dtype_dcn": "bf16"}
    # cooldown: a re-trigger inside the window is recorded, not acted
    # (the knob is on now, so the trigger clears anyway; drop it back
    # off to prove the window itself suppresses)
    c.overrides.pop("wire_dtype_dcn")
    c.observe_step(1, 10.0, _A2A_HEAVY)
    assert c.maybe_act(2) is None
    cd = m.last_decision("controller.cooldown")
    assert cd is not None and cd["trigger"] == "a2a"
    # manifest round trip keeps the spent budget (monotonic)
    sd = c.state_dict()
    assert sd["wire_morphs_used"] == 1
    c2, _ = _ctrl(slices=2)
    c2.load_state_dict(sd)
    assert c2.wire_morphs_used == 1


def test_wire_morph_slices_autodetect(monkeypatch, devices):
    """Production wiring: a controller built WITHOUT slices= (the
    resilient_train / trainer call sites) auto-detects the multi-slice
    topology from the bootstrapped GroupPlan / mocked detection, so
    the wire-morph axis arms on real multi-slice jobs."""
    from flashmoe_tpu.runtime.controller import detected_slices

    monkeypatch.delenv("FLASHMOE_MOCK_SLICES", raising=False)
    assert detected_slices() == 1
    assert RuntimeController(_cfg()).slices == 1
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    assert detected_slices() == 2
    assert RuntimeController(_cfg()).slices == 2
    # detection must never block a step boundary: garbage mock -> 1
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "banana")
    assert detected_slices() == 1


# ----------------------------------------------------------------------
# Speculation morph trigger (ISSUE 20)
# ----------------------------------------------------------------------

def _spec_ctrl(**cc):
    base = dict(debounce_steps=2, cooldown_steps=4, baseline_steps=2,
                ema_decay=0.5, enable_spec_morph=True,
                spec_accept_floor=0.5)
    base.update(cc)
    return _ctrl(ccfg=ControllerConfig(**base))


def test_spec_morph_fires_after_debounce_with_budget():
    from flashmoe_tpu.runtime.controller import SpecMorphAction

    c, m = _spec_ctrl()
    # no-draft observations (None) never debounce toward a morph
    c.observe_spec(0, None)
    assert c._spec_lo_run == 0
    c.observe_spec(0, 0.2)
    assert c.maybe_morph_spec(1) is None      # below the window
    c.observe_spec(1, 0.9)                    # recovery resets the run
    assert c._spec_lo_run == 0
    c.observe_spec(2, 0.2)
    c.observe_spec(3, 0.1)
    act = c.maybe_morph_spec(4)
    assert isinstance(act, SpecMorphAction) and act.kind == "off"
    assert act.trigger == "accept_low"
    rec = m.last_decision("controller.spec_morph")
    assert rec is not None and rec["kind"] == "off"
    assert rec["break_even"] == 0.5
    assert c.spec_morphs_used == 1
    assert c.snapshot()["budgets"]["spec_morph"] == 0
    # budget spent: sustained low acceptance never double-fires
    for s in range(10, 20):
        c.observe_spec(s, 0.0)
    assert c.maybe_morph_spec(20) is None


def test_spec_morph_respects_cooldown_and_spec_off():
    c, m = _spec_ctrl(spec_morph_budget=2)
    c.observe_spec(0, 0.1)
    c.observe_spec(1, 0.1)
    assert c.maybe_morph_spec(2) is not None
    # inside the cooldown window: suppressed (and logged once)
    c.observe_spec(3, 0.1)
    c.observe_spec(4, 0.1)
    assert c.maybe_morph_spec(4) is None
    cd = [d for d in m.decisions
          if d["decision"] == "controller.cooldown"
          and d.get("trigger") == "spec"]
    assert len(cd) == 1
    # spec already off: never acts, whatever the run length
    c.observe_spec(20, 0.0)
    c.observe_spec(21, 0.0)
    assert c.maybe_morph_spec(22, spec_on=False) is None
    # disabled trigger: no action either
    c2, _ = _spec_ctrl(enable_spec_morph=False)
    c2.observe_spec(0, 0.0)
    c2.observe_spec(1, 0.0)
    assert c2.maybe_morph_spec(2) is None


def test_spec_floor_resolution_and_state_roundtrip():
    # no configured floor: the planner break-even feeds the trigger
    c, _ = _spec_ctrl(spec_accept_floor=None)
    c.observe_spec(0, 0.3, break_even=0.4)
    assert c._spec_lo_run == 1
    c.observe_spec(1, 0.3, break_even=0.2)    # above break-even: reset
    assert c._spec_lo_run == 0
    # neither floor nor break-even: observation folds EMA, no trigger
    c.observe_spec(2, 0.1)
    assert c._spec_lo_run == 0
    assert c.spec_accept_ema is not None
    with pytest.raises(ValueError, match="spec_accept_floor"):
        ControllerConfig(spec_accept_floor=1.5)
    # persistence: spec_morphs_used survives a state roundtrip and
    # stays monotonic
    a, _ = _spec_ctrl()
    a.observe_spec(0, 0.1)
    a.observe_spec(1, 0.1)
    assert a.maybe_morph_spec(2) is not None
    b, _ = _spec_ctrl()
    b.load_state_dict(a.state_dict())
    assert b.spec_morphs_used == 1
    assert b.maybe_morph_spec(10) is None     # budget rides the state
