"""The hardware-independent perf harness (VERDICT r4 next #2): the
analytical byte model's orderings are the claims the kernels were built
on — assert them so a refactor that silently regresses traffic fails CI,
and cross-check the model against XLA's own compiled cost analysis where
HLO can see the whole path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.analysis import (
    PathCost, candidate_table, layer_flops, path_costs, xla_cost,
)
from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig

REF = BENCH_CONFIGS["reference"]
F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def test_gather_moves_fewer_bytes_than_explicit():
    """The gather-fused kernel exists to kill the [E, C, H] dispatch
    buffer's write+read; the model must show exactly that delta and
    nothing else moving."""
    ex = path_costs(REF, "explicit")
    ga = path_costs(REF, "gather")
    assert ga.total_bytes < ex.total_bytes
    assert ga.dispatch_bytes == 0.0
    assert ex.dispatch_bytes > 0.0
    # identical FLOPs: it is a data-movement optimization
    assert ga.flops == ex.flops


def test_in_kernel_combine_clears_post_kernel_critical_path():
    """The sorted-return combine's entire point: the combine traffic
    runs inside the kernel (overlapping returns), so nothing remains on
    the post-kernel critical path; the slab variant leaves the full XLA
    combine there."""
    d = 8
    cfg = REF.replace(ep=d)
    slab = path_costs(cfg, "fused", d_world=d)
    fused = path_costs(cfg, "fused_combine", d_world=d)
    assert fused.post_kernel_bytes == 0.0
    assert slab.post_kernel_bytes > 0.0
    # the in-kernel combine reads token-sorted rows (S*K) + a 4-byte
    # weight per row; the XLA combine reads the whole padded slab
    # (slots >= S*K).  At CF=1 slots == S*K exactly, so the sorted read
    # ties and only the tiny weight column separates them
    assert fused.combine_bytes <= slab.combine_bytes * 1.001
    # with real capacity padding the sorted read is strictly smaller
    padded = cfg.replace(capacity_factor=2.0)
    assert (path_costs(padded, "fused_combine", d_world=d).combine_bytes
            < path_costs(padded, "fused", d_world=d).combine_bytes)


def test_fused_weight_restreaming_is_exposed_not_hidden(monkeypatch):
    """The fused kernel's per-source schedules re-stream every local
    expert's weights once per source rank — d_world x the grouped
    kernels' once-per-expert reads (code-review r5 finding #1).  The
    model must CHARGE that, not hide it; and the round-5 arrival-batched
    schedule (own slab at step 0, remotes expert-major at the final
    step) must bring it down to exactly 2x — the schedule's entire
    reason to exist."""
    from flashmoe_tpu import tuning

    d = 8
    cfg = REF.replace(ep=d)
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    xla = path_costs(cfg, "xla", d_world=d)
    # default at d >= 3: the batched schedule, two weight streams
    fused = path_costs(cfg, "fused", d_world=d)
    assert fused.weight_bytes == 2 * xla.weight_bytes
    # per-source schedule (batched disabled): the honest d x cost
    monkeypatch.setenv("FLASHMOE_FUSED_BATCHED", "0")
    tuning._load.cache_clear()
    per_src = path_costs(cfg, "fused", d_world=d)
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED")
    assert per_src.weight_bytes == d * xla.weight_bytes
    # at a single chip there is one source: compute-side traffic (minus
    # the local slab round-trips counted as comm) matches the baseline
    f1 = path_costs(REF, "fused", d_world=1)
    x1 = path_costs(REF, "xla", d_world=1)
    assert f1.weight_bytes == x1.weight_bytes
    assert f1.total_bytes - f1.comm_bytes <= x1.total_bytes * 1.01


def test_resident_schedule_flattens_weight_bytes(tmp_path, monkeypatch):
    """VERDICT r4 weak #4 / next #4: with n_row_tiles > 1 the streaming
    schedule pays n_row_tiles x the weight HBM traffic; the
    weights-resident schedule must hold weight bytes flat (one read per
    expert) at the cost of re-streamed activations."""
    import json

    from flashmoe_tpu import tuning

    # deepseek-ish shape: per-(rank, expert) capacity spans many row
    # tiles, the exact case the resident schedule exists for
    cfg = MoEConfig(num_experts=8, expert_top_k=4, hidden_size=1024,
                    intermediate_size=1408, sequence_len=8192,
                    capacity_factor=1.0, drop_tokens=True, ep=2)

    def with_knob(resident):
        p = tmp_path / f"t{resident}.json"
        p.write_text(json.dumps({"generation": "x", "entries": [{
            "kernel": "fused_ep", "match": {},
            "set": {"weights_resident": resident}}]}))
        monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(p))
        tuning._load.cache_clear()
        try:
            return path_costs(cfg, "fused", d_world=2)
        finally:
            monkeypatch.delenv("FLASHMOE_TUNING_FILE")
            tuning._load.cache_clear()

    resident = with_knob(True)
    streaming = with_knob(False)
    assert resident.weight_bytes < streaming.weight_bytes
    # flat = one stream of each expert's matrices per SOURCE slab (the
    # per-source d_world factor is inherent to the slab grid — see
    # test_fused_weight_restreaming_is_exposed_not_hidden); the resident
    # schedule removes the per-row-tile factor on top of it
    d = 2
    nlx = cfg.num_experts // d
    w_once = nlx * 2 * cfg.hidden_size * cfg.intermediate_size * \
        jnp.dtype(cfg.dtype).itemsize
    assert resident.weight_bytes == w_once * d
    # the trade is explicit: activations re-stream
    assert resident.activation_bytes >= streaming.activation_bytes
    # and at this shape the heuristic chooser must agree with the knob
    monkeypatch.delenv("FLASHMOE_TUNING_FILE", raising=False)
    tuning._load.cache_clear()
    auto = path_costs(cfg, "fused", d_world=2)
    assert auto.weight_bytes == resident.weight_bytes


def test_total_bytes_accounting_is_consistent():
    for p in ("xla", "explicit", "gather", "fused", "fused_combine"):
        c = path_costs(REF.replace(ep=4), p, d_world=4)
        assert isinstance(c, PathCost)
        assert c.total_bytes == pytest.approx(
            c.weight_bytes + c.activation_bytes + c.dispatch_bytes
            + c.comm_bytes + c.combine_bytes)
        assert c.post_kernel_bytes <= c.total_bytes
        assert c.flops > 0


def test_xla_cost_analysis_matches_flop_model():
    """Cross-check the analytical FLOP model against the compiler's own
    cost analysis of the XLA path (HLO sees this path end to end; no
    custom calls hide work).  Small config so the 1-core CPU compile
    stays quick."""
    from flashmoe_tpu.models.reference import init_moe_params
    from flashmoe_tpu.ops.moe import moe_layer

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, **F32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)

    cost = xla_cost(
        lambda p, xx: moe_layer(p, xx, cfg, use_pallas=False).out,
        params, x)
    if cost["flops"] is None:
        pytest.skip("backend cost model reports no flops")
    model = layer_flops(cfg)
    # the XLA path runs the FFN over every padded capacity slot (slots
    # >= S*K) plus routing/one-hot bookkeeping, so the compiled count
    # brackets the model from above but must stay the same order
    assert cost["flops"] >= 0.8 * model
    assert cost["flops"] <= 6.0 * model


def test_xla_dispatch_bytes_match_model():
    """Where HLO sees a whole stage, the byte model must agree with the
    compiler, not just order paths: the dispatch build (plan + gather
    into the capacity buffer) is pure XLA, and its modeled term
    (s*h + slots*h elements) lands within a few percent of the
    compiled cost analysis — anchoring the modeled terms the custom
    calls hide."""
    from flashmoe_tpu.ops import dispatch as dsp

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, **F32)
    cap = cfg.capacity_for(cfg.tokens)

    def build(x, eidx):
        plan = dsp.make_plan(eidx, cfg, cap)
        return dsp.dispatch(x, plan, cfg, cap)

    x = jax.ShapeDtypeStruct((cfg.tokens, cfg.hidden_size), jnp.float32)
    ei = jax.ShapeDtypeStruct((cfg.tokens, cfg.expert_top_k), jnp.int32)
    cost = xla_cost(build, x, ei)
    if cost["bytes"] is None:
        pytest.skip("backend cost model reports no bytes")
    s, h = cfg.tokens, cfg.hidden_size
    slots = cfg.num_experts * cap
    model = (s * h + slots * h) * 4
    # loose bracket: routing bookkeeping (sorts, index planes) adds a
    # few percent on top of the modeled activation movement
    assert model * 0.9 <= cost["bytes"] <= model * 1.5, \
        (cost, model)


def test_schedule_resolution_decision_table(monkeypatch):
    """The BASELINE decision table: which FFN schedule each bench config
    resolves to at d=8.  Since ISSUE 12 the mixtral row is the
    row-windowed schedule's reason to exist: its 14336-wide expert
    hidden slab exceeds VMEM for every weights-once schedule (batched /
    resident stay infeasible), but the window-major rowwin schedule
    bounds weight traffic at exactly 2x the collective path — the
    ACCEPTANCE CRITERION pin: <= 2.5x, vs the 40x the stream fallback
    pays (the pre-rowwin verdict BASELINE.md's caveat reconciles)."""
    from flashmoe_tpu.analysis import _geom
    from flashmoe_tpu.parallel.fused import schedule_table

    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    monkeypatch.delenv("FLASHMOE_FUSED_ROWWIN", raising=False)
    assert _geom(REF, 8)["schedule"] == "batched"
    assert _geom(BENCH_CONFIGS["deepseek"], 8)["schedule"] == "batched"
    assert _geom(BENCH_CONFIGS["weak_scaling_256"], 8)["schedule"] == \
        "batched"
    mix = _geom(BENCH_CONFIGS["mixtral"], 8)
    assert mix["schedule"] == "rowwin"
    t = schedule_table(BENCH_CONFIGS["mixtral"], 8)
    assert not t["feasible"]["batched"] and not t["feasible"]["resident"]
    assert t["feasible"]["rowwin"] and t["kw"] is not None
    fused = path_costs(BENCH_CONFIGS["mixtral"], "fused", d_world=8)
    coll = path_costs(BENCH_CONFIGS["mixtral"], "xla", d_world=8)
    # the ISSUE 12 acceptance bar: modeled mixtral-at-ep=8 fused weight
    # traffic under rowwin <= 2.5x the collective path's (exactly 2x:
    # one K-windowed pass for the own slab, one for the remote batch)
    assert fused.weight_bytes <= 2.5 * coll.weight_bytes
    assert fused.weight_bytes == 2 * coll.weight_bytes
    # the stream fallback's honest 40x stays exposed, not hidden
    stream = path_costs(BENCH_CONFIGS["mixtral"], "fused", d_world=8,
                        schedule="stream")
    assert stream.weight_bytes > 20 * coll.weight_bytes


def test_rowwin_prices_activation_restreaming(monkeypatch):
    """The rowwin schedule's byte trade must be charged, not hidden:
    weight bytes collapse to the 2-pass bound, while the activation
    column grows by the per-window x re-reads AND the f32 partial-sum
    round-trips at every interior window boundary — the term
    BASELINE.md's round-5 caveat demanded before believing any
    row-windowed rescue."""
    from flashmoe_tpu.analysis import _geom

    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    mix = BENCH_CONFIGS["mixtral"]
    g = _geom(mix, 8, schedule="rowwin")
    n_win = g["n_i_chunks"]
    assert n_win > 1  # i=14336 can never be one VMEM window
    rw = path_costs(mix, "fused", d_world=8, schedule="rowwin")
    st = path_costs(mix, "fused", d_world=8, schedule="stream")
    assert rw.weight_bytes < st.weight_bytes
    assert rw.activation_bytes > st.activation_bytes
    slots = 8 * (mix.num_experts // 8) * g["cap"]
    # the accumulator term is exactly (n_win - 1) read+write f32 passes
    acc_bytes = (n_win - 1) * slots * g["h"] * 8.0
    base = path_costs(mix, "fused", d_world=8, schedule="batched")
    # batched at the same window count would re-read x the same number
    # of times (n_i_chunks differs though); assert the rowwin total
    # includes the acc term by reconstruction instead
    x_reads = slots * g["h"] * g["dt"] * n_win
    gate = mix.tokens // 8 * g["h"] * g["dt"] + g["h"] * mix.num_experts * g["dt"]
    y_stage = slots * g["h"] * g["dt"]
    assert rw.activation_bytes == pytest.approx(
        gate + x_reads + y_stage + acc_bytes)
    assert base.flops == rw.flops  # a data-movement schedule, not math


def test_candidate_table_renders():
    t = candidate_table(REF.replace(ep=8), d_world=8)
    assert "fused_combine" in t and "| path |" in t


def test_overlap_bound_reference_v5e8(monkeypatch):
    """The analytical bound a hardware --overlap run is judged against
    (VERDICT r4 next #8), per FFN schedule.  Per-source at the reference
    config on v5e-8 is compute-bound at roofline (C > t_x + C/d), so it
    should hide (almost) all communication: OE well above 1.25.  The
    batched schedule trades some of that overlap for its 2x weight
    streams (only the own slab's C/d hides arrivals, and returns issue
    per expert, so the tail waits t_x/nlx), so its bound sits strictly
    lower — both are reported so a measurement is judged against the
    schedule that actually ran."""
    from flashmoe_tpu.parallel.overlap import overlap_bound

    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    b = overlap_bound(REF, 8, "v5e", schedule="per_source")
    assert b["compute_bound"]
    assert 1.25 <= b["overlap_efficiency_bound"] <= 2.0
    # the default resolution at d=8 is the batched schedule
    bb = overlap_bound(REF, 8, "v5e")
    assert bb["schedule"] == "batched"
    assert 1.0 <= bb["overlap_efficiency_bound"] < \
        b["overlap_efficiency_bound"]
    # calibrated at the measured round-2 mxu_util (0.512): compute
    # stretches, comm stays — the bound must drop toward serialized
    cal = overlap_bound(REF, 8, "v5e", mxu_fraction=0.512,
                        schedule="per_source")
    assert cal["overlap_efficiency_bound"] < b["overlap_efficiency_bound"]
    assert cal["overlap_efficiency_bound"] >= 1.0
    # more ranks shrink per-rank compute faster than per-rank comm
    # (b_dir ~ (d-1)/d), pushing toward the comm-bound regime
    b64 = overlap_bound(REF, 64, "v5e", schedule="per_source")
    assert b64["t_x_ms"] / b64["compute_ms"] > \
        b["t_x_ms"] / b["compute_ms"]
