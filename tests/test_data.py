"""Native + fallback data loader: determinism, shuffling, epochs."""

import numpy as np
import pytest

from flashmoe_tpu.parallel import _native
from flashmoe_tpu.runtime.data import TokenLoader, write_token_file


@pytest.fixture()
def token_file(tmp_path):
    p = str(tmp_path / "tokens.bin")
    write_token_file(p, np.arange(33 * 40, dtype=np.int32))  # 40 windows @ 33
    return p


def test_fallback_iterates(token_file):
    ld = TokenLoader(token_file, batch=4, seq_len=32, shuffle=False,
                     native=False)
    assert ld.num_windows == 40
    b1 = next(ld)["tokens"]
    assert b1.shape == (4, 33)
    np.testing.assert_array_equal(np.asarray(b1[0]), np.arange(33))
    np.testing.assert_array_equal(np.asarray(b1[1]), np.arange(33, 66))


def test_shuffle_deterministic_and_complete(token_file):
    a = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    b = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    firsts = []
    for _ in range(10):  # one full epoch
        ba, bb = next(a)["tokens"], next(b)["tokens"]
        np.testing.assert_array_equal(np.asarray(ba), np.asarray(bb))
        firsts.extend(int(r[0]) for r in np.asarray(ba))
    # each window starts at a multiple of 33; one epoch covers all 40
    assert sorted(firsts) == [33 * i for i in range(40)]


def test_native_matches_fallback(token_file):
    if _native.load() is None:
        pytest.skip("native library unavailable")
    nat = TokenLoader(token_file, batch=4, seq_len=32, seed=7)
    fb = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    assert nat.is_native
    for _ in range(12):  # crosses an epoch boundary
        np.testing.assert_array_equal(
            np.asarray(next(nat)["tokens"]), np.asarray(next(fb)["tokens"])
        )
    nat.close()


def test_closed_loader_raises_clear_error(token_file):
    """Satellite: a closed native loader used to fall through to the
    NumPy branch and die with AttributeError: _windows."""
    for native in (False, "auto"):
        ld = TokenLoader(token_file, batch=4, seq_len=32, native=native)
        next(ld)
        ld.close()
        ld.close()  # idempotent on both paths
        with pytest.raises(RuntimeError, match="loader is closed"):
            next(ld)
        with pytest.raises(RuntimeError, match="loader is closed"):
            ld.state_dict()


def test_state_dict_roundtrip_mid_epoch(token_file):
    a = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    for _ in range(3):
        next(a)
    b = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    b.load_state_dict(a.state_dict())
    for _ in range(12):  # runs past the epoch boundary too
        np.testing.assert_array_equal(
            np.asarray(next(a)["tokens"]), np.asarray(next(b)["tokens"]))


def test_state_dict_roundtrip_across_epoch_boundary(token_file):
    """Resume from the exact epoch boundary: the lazy NumPy wrap and the
    canonical (epoch+1, 0) state must produce the same continuation."""
    a = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    for _ in range(10):  # exactly one epoch of 40 windows
        next(a)
    st = a.state_dict()
    assert st == {"epoch": 1, "cursor": 0, "seed": 7, "shuffle": True}
    b = TokenLoader(token_file, batch=4, seq_len=32, seed=7, native=False)
    b.load_state_dict(st)
    for _ in range(6):
        np.testing.assert_array_equal(
            np.asarray(next(a)["tokens"]), np.asarray(next(b)["tokens"]))


def test_state_dict_native_matches_fallback(token_file):
    """The canonical state is path-independent: equal dicts after equal
    consumption, and a native loader restores a fallback's state (and
    vice versa) onto the identical stream."""
    if _native.load() is None:
        pytest.skip("native library unavailable")
    nat = TokenLoader(token_file, batch=4, seq_len=32, seed=7)
    fb = TokenLoader(token_file, batch=4, seq_len=32, seed=7,
                     native=False)
    assert nat.is_native
    for _ in range(5):
        next(nat)
        next(fb)
    assert nat.state_dict() == fb.state_dict()

    # cross-restore: native <- fallback state (fast-forward reopen)
    nat2 = TokenLoader(token_file, batch=4, seq_len=32, seed=7)
    nat2.load_state_dict(fb.state_dict())
    # fallback <- native state
    fb2 = TokenLoader(token_file, batch=4, seq_len=32, seed=7,
                      native=False)
    fb2.load_state_dict(nat.state_dict())
    for _ in range(8):
        want = np.asarray(next(fb)["tokens"])
        np.testing.assert_array_equal(np.asarray(next(nat2)["tokens"]),
                                      want)
        np.testing.assert_array_equal(np.asarray(next(fb2)["tokens"]),
                                      want)
    nat.close()
    nat2.close()


def test_load_state_dict_validates_cursor(token_file):
    ld = TokenLoader(token_file, batch=4, seq_len=32, native=False)
    with pytest.raises(ValueError, match="out of range"):
        ld.load_state_dict({"epoch": 0, "cursor": 40, "seed": 0,
                            "shuffle": False})


def test_feeds_trainer(token_file, devices):
    import jax
    import jax.numpy as jnp
    from flashmoe_tpu.config import MoEConfig
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.runtime.trainer import train

    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=32, num_layers=1,
                    moe_frequency=1, vocab_size=2048, num_heads=2,
                    drop_tokens=False, is_training=True, ep=4,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    mesh = make_mesh(cfg)
    ld = TokenLoader(token_file, batch=2, seq_len=32, native=False)
    state, hist = train(cfg, mesh, ld, num_steps=2, log_every=1)
    assert len(hist) == 2 and np.isfinite(hist[-1]["loss"])
