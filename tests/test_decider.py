"""Decider (placement optimizer) and topology cost model.

The synthetic 8-device / 2-island scenario mirrors the reference's only
decider harness (``csrc/correctness/eval.cuh:142-233``).
"""

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.decider import (
    decide, ring_allreduce_ms, uniform_placement,
)
from flashmoe_tpu.parallel.topology import (
    Adjacency, WorkerAttr, ici_adjacency,
)


def _island_adj(n=8, cut=4, slow_alpha=0.5, slow_beta=0.05):
    alpha = np.full((n, n), 0.01)
    beta = np.full((n, n), 0.001)
    for i in range(n):
        for j in range(n):
            if (i < cut) != (j < cut):
                alpha[i, j] = slow_alpha
                beta[i, j] = slow_beta
        alpha[i, i] = beta[i, i] = 0
    return Adjacency(alpha, beta)


def _workers(n=8, thr=1.0, mem=16.0):
    return [WorkerAttr(throughput=thr, memory_gb=mem) for _ in range(n)]


def test_all_experts_assigned():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    p = decide(_island_adj(), _workers(), cfg)
    assigned = sorted(e for d in p.groups[0] for e in p.local_experts[d])
    assert assigned == list(range(16))


def test_homogeneous_uniform_split():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    p = decide(_island_adj(), _workers(), cfg)
    for g in p.groups:
        sizes = [len(p.local_experts[d]) for d in g]
        assert max(sizes) - min(sizes) <= 1


def test_heterogeneous_rate_proportional():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    workers = [
        WorkerAttr(throughput=3.0 if d < 2 else 1.0, memory_gb=16.0)
        for d in range(8)
    ]
    p = decide(_island_adj(), workers, cfg)
    fast = len(p.local_experts[0])
    slow = len(p.local_experts[7])
    assert fast > slow


def test_expensive_comm_keeps_islands_separate():
    """With extreme inter-island cost and big activations, merging would
    regress the objective — two DP groups must survive."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=4096,
                    sequence_len=8192, mini_batch=4)
    adj = _island_adj(slow_alpha=1000.0, slow_beta=100.0)
    p = decide(adj, _workers(), cfg)
    assert len(p.groups) == 2
    assert sorted(p.groups[0]) == [0, 1, 2, 3]
    # each group holds the full expert set (DP replicas)
    for g in p.groups:
        assigned = sorted(e for d in g for e in p.local_experts[d])
        assert assigned == list(range(8))


def test_memory_infeasible_groups_merge():
    """Devices too small to hold all experts alone must end up grouped."""
    cfg = MoEConfig(num_experts=64, expert_top_k=2, hidden_size=4096,
                    intermediate_size=4096)
    # each expert ~134MB f32; 64 experts ~8.6GB; give devices 2GB each
    workers = _workers(mem=2.0)
    adj = _island_adj(slow_alpha=1000.0, slow_beta=100.0)
    p = decide(adj, workers, cfg)
    for g in p.groups:
        cap = sum(2.0 for _ in g) * 1024
        assert cap >= 64 * (2 * 4096 * 4096 * 4 / 1e6)


def test_ring_allreduce_model():
    assert ring_allreduce_ms(100.0, 1, 0.1) == 0.0
    t2 = ring_allreduce_ms(100.0, 2, 0.1)
    t4 = ring_allreduce_ms(100.0, 4, 0.1)
    assert t2 > 0 and t4 > t2


def test_uniform_placement():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    p = uniform_placement(4, cfg)
    assert p.local_experts[0] == [0, 1, 2, 3]
    assert p.local_experts[3] == [12, 13, 14, 15]


def test_ici_adjacency_virtual_devices():
    adj = ici_adjacency()
    assert adj.n >= 1
    assert (adj.alpha >= 0).all() and (adj.beta >= 0).all()
    assert np.all(np.diag(adj.alpha) == 0)
