"""Decider (placement optimizer) and topology cost model.

The synthetic 8-device / 2-island scenario mirrors the reference's only
decider harness (``csrc/correctness/eval.cuh:142-233``).
"""

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.decider import (
    decide, ring_allreduce_ms, uniform_placement,
)
from flashmoe_tpu.parallel.topology import (
    Adjacency, WorkerAttr, ici_adjacency,
)


def _island_adj(n=8, cut=4, slow_alpha=0.5, slow_beta=0.05):
    alpha = np.full((n, n), 0.01)
    beta = np.full((n, n), 0.001)
    for i in range(n):
        for j in range(n):
            if (i < cut) != (j < cut):
                alpha[i, j] = slow_alpha
                beta[i, j] = slow_beta
        alpha[i, i] = beta[i, i] = 0
    return Adjacency(alpha, beta)


def _workers(n=8, thr=1.0, mem=16.0):
    return [WorkerAttr(throughput=thr, memory_gb=mem) for _ in range(n)]


def test_all_experts_assigned():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    p = decide(_island_adj(), _workers(), cfg)
    assigned = sorted(e for d in p.groups[0] for e in p.local_experts[d])
    assert assigned == list(range(16))


def test_homogeneous_uniform_split():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    p = decide(_island_adj(), _workers(), cfg)
    for g in p.groups:
        sizes = [len(p.local_experts[d]) for d in g]
        assert max(sizes) - min(sizes) <= 1


def test_heterogeneous_rate_proportional():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    workers = [
        WorkerAttr(throughput=3.0 if d < 2 else 1.0, memory_gb=16.0)
        for d in range(8)
    ]
    p = decide(_island_adj(), workers, cfg)
    fast = len(p.local_experts[0])
    slow = len(p.local_experts[7])
    assert fast > slow


def test_expensive_comm_keeps_islands_separate():
    """With extreme inter-island cost and big activations, merging would
    regress the objective — two DP groups must survive."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=4096,
                    sequence_len=8192, mini_batch=4)
    adj = _island_adj(slow_alpha=1000.0, slow_beta=100.0)
    p = decide(adj, _workers(), cfg)
    assert len(p.groups) == 2
    assert sorted(p.groups[0]) == [0, 1, 2, 3]
    # each group holds the full expert set (DP replicas)
    for g in p.groups:
        assigned = sorted(e for d in g for e in p.local_experts[d])
        assert assigned == list(range(8))


def test_memory_infeasible_groups_merge():
    """Devices too small to hold all experts alone must end up grouped."""
    cfg = MoEConfig(num_experts=64, expert_top_k=2, hidden_size=4096,
                    intermediate_size=4096)
    # each expert ~134MB f32; 64 experts ~8.6GB; give devices 2GB each
    workers = _workers(mem=2.0)
    adj = _island_adj(slow_alpha=1000.0, slow_beta=100.0)
    p = decide(adj, workers, cfg)
    for g in p.groups:
        cap = sum(2.0 for _ in g) * 1024
        assert cap >= 64 * (2 * 4096 * 4096 * 4 / 1e6)


def _gateway_adj():
    """Two 2-device islands; cross links are DCN-like: huge alpha, small
    beta.  The global max beta lives on island A's (slower-gen) INTERNAL
    link — exactly the case where beta-only allreduce pricing is blind."""
    n = 4
    alpha = np.zeros((n, n))
    beta = np.zeros((n, n))
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            if (i < 2) == (j < 2):
                beta[i, j] = 0.05 if i < 2 else 0.001  # A slow-ICI, B fast
            else:
                alpha[i, j] = 10.0   # DCN latency dominates
                beta[i, j] = 0.002
    return Adjacency(alpha, beta)


def _true_step_time(groups, adj, cfg, rates, act_mb, grad_mb, gamma):
    """Ground-truth step model: slowest group's compute+intra, plus the
    ring allreduce over the actual worst external edge."""
    n = adj.n
    worst_grp = 0.0
    for g in groups:
        rate = sum(rates[d] for d in g)
        compute = (cfg.num_experts / min(rates)) / rate
        intra = max(
            (adj.transfer_ms(i, j, act_mb / len(g))
             for i in g for j in g if i != j), default=0.0)
        worst_grp = max(worst_grp, gamma * (compute + intra))
    ar = 0.0
    if len(groups) > 1:
        owner = {d: gi for gi, g in enumerate(groups) for d in g}
        bot = max(adj.transfer_ms(i, j, grad_mb / len(groups))
                  for i in range(n) for j in range(n)
                  if i != j and owner[i] != owner[j])
        ar = 2.0 * (len(groups) - 1) * bot
    return worst_grp + ar


def test_bottleneck_edge_pricing_beats_max_beta():
    """VERDICT r2 #6: the reference prices the inter-group allreduce with
    the actual bottleneck EDGE (alpha included, intra-group edges
    excluded) via a priority queue; the round-2 global-max-beta model is
    blind to DCN latency and must produce a different — and worse —
    grouping here."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=128,
                    vocab_size=8192, num_layers=1, is_training=True)
    adj = _gateway_adj()
    workers = _workers(n=4)
    p_new = decide(adj, workers, cfg, native=False)
    p_old = decide(adj, workers, cfg, native=False, price_mode="max_beta")
    # beta-only pricing underprices the 2x10ms-per-step DCN allreduce and
    # keeps the islands as separate DP groups; edge pricing sees it and
    # merges into one group
    assert len(p_new.groups) == 1
    assert len(p_old.groups) == 2
    rates = [w.throughput for w in workers]
    act_mb = cfg.tokens * cfg.hidden_size * 4 / 1e6
    grad_mb = cfg.param_count * 4 / 1e6
    t_new = _true_step_time(p_new.groups, adj, cfg, rates, act_mb,
                            grad_mb, gamma=cfg.num_layers)
    t_old = _true_step_time(p_old.groups, adj, cfg, rates, act_mb,
                            grad_mb, gamma=cfg.num_layers)
    assert t_new < t_old


def test_inference_mode_skips_allreduce_pressure():
    """The inference Decider specialization (decider.cuh:177-268) has no
    allreduce term: with the same topology the islands stay separate,
    while the training Decider merges them to dodge the DCN allreduce."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=128,
                    vocab_size=8192, num_layers=1, is_training=False)
    adj = _gateway_adj()
    p_inf = decide(adj, _workers(n=4), cfg, native=False)
    assert len(p_inf.groups) == 2
    p_trn = decide(adj, _workers(n=4), cfg.replace(is_training=True),
                   native=False)
    assert len(p_trn.groups) == 1


def test_ring_allreduce_model():
    assert ring_allreduce_ms(100.0, 1, 0.1) == 0.0
    t2 = ring_allreduce_ms(100.0, 2, 0.1)
    t4 = ring_allreduce_ms(100.0, 4, 0.1)
    assert t2 > 0 and t4 > t2


def test_uniform_placement():
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    p = uniform_placement(4, cfg)
    assert p.local_experts[0] == [0, 1, 2, 3]
    assert p.local_experts[3] == [12, 13, 14, 15]


def test_ici_adjacency_virtual_devices():
    adj = ici_adjacency()
    assert adj.n >= 1
    assert (adj.alpha >= 0).all() and (adj.beta >= 0).all()
    assert np.all(np.diag(adj.alpha) == 0)


# ----------------------------------------------------------------------
# Skewed-rate assignment, replication, and the runtime re-placement
# projection (the self-healing controller's Decider entry points)
# ----------------------------------------------------------------------

from flashmoe_tpu.parallel.decider import (  # noqa: E402
    assign_experts, placement_permutation, rebalance_placement,
)


def _flat_adj(n=4):
    alpha = np.full((n, n), 0.01)
    beta = np.full((n, n), 0.001)
    np.fill_diagonal(alpha, 0)
    np.fill_diagonal(beta, 0)
    return Adjacency(alpha, beta)


def test_decide_skewed_costs_isolates_hot_expert():
    """Cost-sorted multiset: the device hosting the hot expert carries
    fewer cold neighbors, so per-device COST (not count) balances."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2)
    costs = np.ones(8)
    costs[0] = 10.0
    p = decide(_flat_adj(), _workers(n=4), cfg, expert_costs=costs)
    hot_dev = p.expert_owner[0]
    assert len(p.local_experts[hot_dev]) < max(
        len(v) for d, v in p.local_experts.items() if d != hot_dev)
    loads = [sum(costs[e] for e in p.local_experts[d]) for d in range(4)]
    assert max(loads) / min(loads) < 10.0 / 1.0  # far better than naive
    # every expert assigned exactly once (no replication requested)
    assigned = sorted(e for d in range(4) for e in p.local_experts[d])
    assert assigned == list(range(8))


def test_decide_skewed_rates_feed_cold_tail_to_slow_device():
    cfg = MoEConfig(num_experts=8, expert_top_k=2)
    costs = np.ones(8)
    costs[0] = 8.0
    workers = [WorkerAttr(throughput=0.25 if d == 0 else 1.0,
                          memory_gb=16.0) for d in range(4)]
    p = decide(_flat_adj(), workers, cfg, expert_costs=costs)
    # the slow device must not own the hot expert
    assert p.expert_owner[0] != 0
    slow_cost = sum(costs[e] for e in p.local_experts[0])
    assert slow_cost <= min(
        sum(costs[e] for e in p.local_experts[d]) for d in range(1, 4))


def test_decide_replicates_hot_expert_when_capacity_allows():
    cfg = MoEConfig(num_experts=8, expert_top_k=2)
    costs = np.ones(8)
    costs[0] = 10.0
    p = decide(_flat_adj(), _workers(n=4, mem=64.0), cfg,
               expert_costs=costs, replicate=True)
    assert 0 in p.replicas and p.replicas[0]
    extra = p.replicas[0][0]
    assert extra != p.expert_owner[0]
    assert 0 in p.local_experts[extra]
    # tight memory: no spare slot, no replica
    tight = [WorkerAttr(throughput=1.0, memory_gb=0.001)
             for _ in range(4)]
    p2 = decide(_flat_adj(), tight, cfg, expert_costs=costs,
                replicate=True)
    assert p2.replicas == {}


def test_decide_skewed_is_deterministic():
    """Stability: identical inputs -> identical Placement (the
    controller's replan-from-unchanged-telemetry no-op guarantee)."""
    cfg = MoEConfig(num_experts=16, expert_top_k=2)
    costs = np.linspace(3.0, 1.0, 16)
    costs[5] = 20.0
    workers = [WorkerAttr(throughput=1.0 + 0.5 * (d % 2), memory_gb=64.0)
               for d in range(4)]
    runs = [decide(_flat_adj(), workers, cfg, expert_costs=costs.copy(),
                   replicate=True) for _ in range(3)]
    for p in runs[1:]:
        assert p.groups == runs[0].groups
        assert p.local_experts == runs[0].local_experts
        assert p.replicas == runs[0].replicas


def test_assign_experts_uniform_matches_contiguous_split():
    out = assign_experts([0, 1, 2, 3], [1.0, 1.0, 1.0, 1.0], 8)
    assert out == {0: [0, 1], 1: [2, 3], 2: [4, 5], 3: [6, 7]}


def test_assign_experts_rejects_bad_cost_shape():
    import pytest

    with pytest.raises(ValueError, match="shape"):
        assign_experts([0, 1], [1.0, 1.0], 4, expert_costs=np.ones(3))


def test_rebalance_placement_equal_slots_and_rates():
    """The runtime projection: equal slot counts per device, hot slot
    off the slow device, deterministic, and the permutation encoding
    round-trips."""
    cfg = MoEConfig(num_experts=8, expert_top_k=1)
    loads = np.zeros(8)
    loads[0] = 64.0
    rates = np.array([0.25, 1.0, 1.0, 1.0])
    p = rebalance_placement(loads, 4, cfg, rates=rates)
    assert all(len(p.local_experts[d]) == 2 for d in range(4))
    assert p.expert_owner[0] != 0  # hot slot leaves the slow device
    perm = placement_permutation(p)
    assert sorted(perm) == list(range(8))
    p2 = rebalance_placement(loads, 4, cfg, rates=rates)
    assert placement_permutation(p2) == perm


def test_rebalance_placement_replicates_onto_dead_slot():
    cfg = MoEConfig(num_experts=8, expert_top_k=1)
    loads = np.zeros(8)
    loads[0] = 64.0
    p = rebalance_placement(loads, 4, cfg,
                            rates=np.array([0.25, 1.0, 1.0, 1.0]),
                            replicate=True)
    assert len(p.replicas) == 1
    (hot_slot, victims), = p.replicas.items()
    perm = placement_permutation(p)
    assert perm[hot_slot] == 0          # the hot expert's new slot
    assert perm[victims[0]] != 0        # victim is a dead slot
    # replica lands on a different device than the hot slot
    nlx = 2
    assert hot_slot // nlx != victims[0] // nlx


def test_rebalance_placement_balanced_no_worse_no_replicas():
    """Uniform loads: the projection may pick any equal split, but the
    per-device totals must match the identity layout's (the controller
    then treats it as a noop via its min-gain guard) and nothing is
    replicated."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2)
    p = rebalance_placement(np.ones(8), 4, cfg)
    assert [len(p.local_experts[d]) for d in range(4)] == [2, 2, 2, 2]
    assert p.replicas == {}


def test_rebalance_placement_validates_inputs():
    import pytest

    cfg = MoEConfig(num_experts=8, expert_top_k=2)
    with pytest.raises(ValueError, match="divide"):
        rebalance_placement(np.ones(8), 3, cfg)
    with pytest.raises(ValueError, match="shape"):
        rebalance_placement(np.ones(7), 4, cfg)
