"""Dispatch/combine permutation invariants."""

import jax
import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.ops import dispatch as dsp

CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                sequence_len=128, dtype=jnp.float32, param_dtype=jnp.float32)


def _idx(cfg, seed=0):
    key = jax.random.PRNGKey(seed)
    return jax.random.randint(
        key, (cfg.tokens, cfg.expert_top_k), 0, cfg.num_experts, jnp.int32
    )


def test_positions_unique_per_expert():
    idx = _idx(CFG)
    plan = dsp.make_plan(idx, CFG, capacity=CFG.tokens)
    # (expert, position) pairs must be unique across all (s, k)
    pairs = np.asarray(
        plan.expert_idx * CFG.tokens + plan.position
    ).reshape(-1)
    assert len(np.unique(pairs)) == pairs.size


def test_k_major_priority():
    """All k=0 assignments must rank before any k=1 assignment of the same
    expert (GShard priority — mirrors the reference's slot ordering)."""
    idx = jnp.array([[0, 1], [1, 0], [0, 1]], jnp.int32)
    cfg = MoEConfig(num_experts=2, expert_top_k=2, hidden_size=64,
                    sequence_len=128)
    plan = dsp.make_plan(idx, cfg, capacity=8)
    pos = np.asarray(plan.position)
    # expert 0 k=0 selections: tokens 0,2 -> pos 0,1; token 1 k=1 -> pos 2
    assert pos[0, 0] == 0 and pos[2, 0] == 1 and pos[1, 1] == 2
    # expert 1: token 1 k=0 -> pos 0; tokens 0,2 k=1 -> pos 1,2
    assert pos[1, 0] == 0 and pos[0, 1] == 1 and pos[2, 1] == 2


def test_plan_matches_bruteforce_oracle():
    """Sort-based plan == arrival-order counting (the cumsum semantics)."""
    rng = np.random.default_rng(0)
    for _ in range(10):
        s = int(rng.integers(8, 200))
        k = int(rng.integers(1, 4))
        e = int(rng.integers(2, 17))
        idx = rng.integers(0, e, size=(s, k)).astype(np.int32)
        cfg = MoEConfig(num_experts=e, expert_top_k=k, hidden_size=64,
                        intermediate_size=64, sequence_len=max(8, s))
        cap = int(rng.integers(1, 2 * s))
        plan = dsp.make_plan(jnp.asarray(idx), cfg, cap)
        cnt = np.zeros(e, np.int64)
        pos = np.zeros((s, k), np.int64)
        for kk in range(k):          # k-major arrival order
            for ss in range(s):
                ex = idx[ss, kk]
                pos[ss, kk] = cnt[ex]
                cnt[ex] += 1
        np.testing.assert_array_equal(np.asarray(plan.position), pos)
        np.testing.assert_array_equal(np.asarray(plan.counts), cnt)
        np.testing.assert_array_equal(np.asarray(plan.valid), pos < cap)


def test_dispatch_indices_consistent_with_plan():
    """src_tok slots agree with (expert, position) scatter of token ids."""
    idx = _idx(CFG, seed=3)
    cap = 80
    plan = dsp.make_plan(idx, CFG, cap)
    src_tok, present = dsp.dispatch_indices(plan, CFG, cap)
    src_tok, present = np.asarray(src_tok), np.asarray(present)
    pos = np.asarray(plan.position)
    valid = np.asarray(plan.valid)
    eidx = np.asarray(plan.expert_idx)
    s, k = eidx.shape
    for ss in range(s):
        for kk in range(k):
            if valid[ss, kk]:
                assert present[eidx[ss, kk], pos[ss, kk]]
                assert src_tok[eidx[ss, kk], pos[ss, kk]] == ss


def test_dispatch_combine_roundtrip_identity():
    """With identity 'experts' and no drops, combine(dispatch(x)) == x."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    sequence_len=128, drop_tokens=False)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 64), jnp.float32)
    idx = _idx(cfg)
    # force distinct experts per token so weights stay meaningful
    idx = idx.at[:, 1].set((idx[:, 0] + 1) % cfg.num_experts)
    w = jnp.full((cfg.tokens, 2), 0.5, jnp.float32)
    plan = dsp.make_plan(idx, cfg, cfg.tokens)
    buf = dsp.dispatch(x, plan, cfg, cfg.tokens)
    out = dsp.combine(buf, plan, w, cfg, cfg.tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


def test_capacity_drop():
    """Positions beyond capacity are marked invalid and dropped tokens'
    weight mass renormalizes onto surviving slots."""
    cfg = MoEConfig(num_experts=2, expert_top_k=1, hidden_size=64,
                    sequence_len=128, drop_tokens=True)
    # all tokens to expert 0, capacity 4 -> only 4 survive
    idx = jnp.zeros((16, 1), jnp.int32)
    plan = dsp.make_plan(idx, cfg, capacity=4)
    assert int(jnp.sum(plan.valid)) == 4
    x = jnp.ones((16, 64), jnp.float32)
    buf = dsp.dispatch(x, plan, cfg, 4)
    assert float(jnp.sum(buf)) == 4 * 64  # exactly 4 rows written
    w = jnp.ones((16, 1), jnp.float32)
    out = dsp.combine(buf, plan, w, cfg, 4)
    # dropped tokens produce zeros; surviving produce x
    kept = np.asarray(plan.valid[:, 0])
    np.testing.assert_allclose(np.asarray(out)[kept], 1.0)
    np.testing.assert_allclose(np.asarray(out)[~kept], 0.0)
