"""Elastic resume folding: edge cases of fold_parallelism.

The happy path (ep=4 -> ep=2 on half the devices) is covered by
tests/test_resilient.py; these pin the awkward corners — prime device
counts, expert counts no candidate ep divides, and the loud warning when
pp/tp/sp axes are dropped."""

import warnings

import jax.numpy as jnp
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.runtime.elastic import fold_parallelism

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _cfg(**kw):
    base = dict(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=32, num_layers=1,
                vocab_size=256, num_heads=2, is_training=True, **F32)
    base.update(kw)
    return MoEConfig(**base)


def _check_valid(cfg: MoEConfig, n: int):
    """The folded config must satisfy its own invariants and tile the
    device count exactly (dp * ep == n, experts divide over ep)."""
    assert cfg.ep * cfg.dp == n
    assert cfg.pp == cfg.tp == cfg.sp == 1
    if cfg.num_experts > 1:
        assert cfg.num_experts % cfg.ep == 0
    # replace() re-runs __post_init__ validation on the folded values
    cfg.replace()


def test_prime_device_count_folds_to_dp():
    """7 devices: no ep > 1 divides both 7 and num_experts=4, so the job
    resumes pure-dp."""
    folded = fold_parallelism(_cfg(ep=4), 7)
    assert folded.ep == 1 and folded.dp == 7
    _check_valid(folded, 7)


def test_prime_expert_count_folds_to_dp():
    """num_experts=7 (prime) on 4 devices: ep can only be 1."""
    folded = fold_parallelism(_cfg(num_experts=7, expert_top_k=2, ep=1), 4)
    assert folded.ep == 1 and folded.dp == 4
    _check_valid(folded, 4)


def test_experts_indivisible_by_full_world():
    """num_experts=6, ep=6 job lands on 4 devices: candidate ep=4 fails
    (6 % 4), ep=3 fails (4 % 3), ep=2 divides both — the largest ep
    that satisfies BOTH divisibility constraints wins."""
    folded = fold_parallelism(_cfg(num_experts=6, ep=6), 4)
    assert folded.ep == 2 and folded.dp == 2
    _check_valid(folded, 4)


def test_single_device_always_valid():
    folded = fold_parallelism(_cfg(ep=4), 1)
    assert folded.ep == 1 and folded.dp == 1
    _check_valid(folded, 1)


def test_ep_grows_to_world_when_unpinned():
    """ep=1 configs let the fold claim every device for ep when the
    expert count allows it (ep = min(n_devices, ...))."""
    folded = fold_parallelism(_cfg(num_experts=8, ep=1), 4)
    assert folded.ep == 4 and folded.dp == 1
    _check_valid(folded, 4)


@pytest.mark.parametrize("axis", ["pp", "tp", "sp"])
def test_dropped_axis_warns(axis):
    cfg = _cfg(ep=2, **{axis: 2})
    with pytest.warns(UserWarning, match=f"dropping {axis}=2"):
        folded = fold_parallelism(cfg, 4)
    _check_valid(folded, 4)


def test_multiple_dropped_axes_warn_once_with_all_names():
    cfg = _cfg(ep=2, pp=2, tp=2)
    with pytest.warns(UserWarning) as rec:
        folded = fold_parallelism(cfg, 8)
    msgs = [str(w.message) for w in rec
            if "folds parallelism" in str(w.message)]
    assert len(msgs) == 1
    assert "pp=2" in msgs[0] and "tp=2" in msgs[0]
    _check_valid(folded, 8)


def test_clean_dp_ep_config_folds_silently():
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        folded = fold_parallelism(_cfg(ep=2), 6)
    assert folded.ep == 2 and folded.dp == 3
    _check_valid(folded, 6)


def test_guarded_checkpoint_without_guard_arg_raises_clearly(devices,
                                                             tmp_path):
    """Satellite: restoring a guard-carrying checkpoint without guard=
    used to die inside orbax with an opaque tree-structure error; it
    must name the mismatch and the fix instead."""
    import jax
    import pytest as _pytest

    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.runtime import checkpoint as ckpt
    from flashmoe_tpu.runtime.elastic import elastic_resume
    from flashmoe_tpu.runtime.trainer import (
        GradGuardConfig, init_state, make_optimizer, state_shardings,
    )

    cfg = _cfg(ep=1, moe_frequency=1, num_heads=2)
    guard = GradGuardConfig(warmup_steps=2)
    mesh = make_mesh(cfg, dp=1, devices=devices[:1])
    opt = make_optimizer(cfg, total_steps=4)
    state = init_state(jax.random.PRNGKey(0), cfg, opt, guard=guard)
    state = jax.device_put(state, state_shardings(state, cfg, mesh))
    d = str(tmp_path / "ck_guarded")
    ckpt.save(d, state, step=1)

    with _pytest.raises(ValueError, match="GuardState.*guard="):
        elastic_resume(cfg, d, devices=devices[:1])

    # the matching call restores fine
    restored, _mesh, _cfg2, _opt = elastic_resume(
        cfg, d, devices=devices[:1], guard=guard)
    assert restored.guard is not None
