"""Expert-parallel MoE layer on the virtual 8-device mesh vs the oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import Activation, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.parallel.ep import ep_moe_layer, local_capacity
from flashmoe_tpu.parallel.mesh import make_mesh

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _setup(cfg, seed=0):
    pk, xk = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(pk, cfg)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), jnp.float32)
    return params, x


@pytest.mark.parametrize("ep", [2, 4, 8])
def test_ep_matches_oracle_nodrop(ep, devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256,
                    drop_tokens=False, ep=ep, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:ep])
    out = ep_moe_layer(params, x, cfg, mesh)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    assert int(jnp.sum(out.expert_counts)) == cfg.tokens * cfg.expert_top_k


def test_ep_gated_shared(devices):
    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256,
                    drop_tokens=False, ep=8, gated_ffn=True,
                    hidden_act=Activation.SILU, num_shared_experts=1, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1)
    out = ep_moe_layer(params, x, cfg, mesh)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ep_matches_single_device_with_drops(devices):
    """With per-shard capacity limits, EP must equal the single-device layer
    run shard-by-shard (same drops, same renormalization)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=512,
                    capacity_factor=1.0, drop_tokens=True, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1)
    out = ep_moe_layer(params, x, cfg, mesh)

    d = 8
    s_loc = cfg.tokens // d
    cap = local_capacity(cfg, s_loc)
    chunks = []
    for r in range(d):
        shard = x[r * s_loc:(r + 1) * s_loc]
        o = moe_layer(params, shard, cfg, use_pallas=False, capacity=cap)
        chunks.append(o.out)
    want = jnp.concatenate(chunks, axis=0)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_ep_with_tensor_parallel_experts(devices):
    """EP x TP: experts over ep, each expert's intermediate dim Megatron-
    split over tp (one psum per FFN)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256,
                    drop_tokens=False, ep=2, tp=2, gated_ffn=True,
                    hidden_act=Activation.SILU, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg)  # dp=2, ep=2, tp=2 on 8 devices
    out = ep_moe_layer(params, x, cfg, mesh, token_axes=("dp", "ep"))
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("inner", [2, 4])
@pytest.mark.slow
def test_hierarchical_dcn_a2a_matches_flat(inner, devices):
    """Two-stage (intra-slice, inter-slice) all-to-all must be
    bit-identical to the flat exchange."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256,
                    drop_tokens=False, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    flat = ep_moe_layer(params, x, cfg, mesh)
    hier = ep_moe_layer(params, x, cfg, mesh, dcn_inner=inner)
    np.testing.assert_array_equal(
        np.asarray(flat.out), np.asarray(hier.out)
    )


@pytest.mark.slow
def test_ep_pallas_path_and_grad(devices):
    """EP with pallas experts (interpreter): forward matches oracle and
    the custom-VJP backward produces finite grads."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256,
                    drop_tokens=False, ep=4, is_training=True, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    out = ep_moe_layer(params, x, cfg, mesh, use_pallas=True, interpret=True)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )

    def loss(p):
        o = ep_moe_layer(p, x, cfg, mesh, use_pallas=True, interpret=True)
        return jnp.sum(o.out ** 2) + o.aux_loss

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.slow
def test_ep_grad(devices):
    """EP layer must be differentiable end-to-end (training path)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=64, sequence_len=128,
                    drop_tokens=False, ep=8, is_training=True, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1)

    def loss(p):
        o = ep_moe_layer(p, x, cfg, mesh)
        return jnp.sum(o.out ** 2) + o.aux_loss

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
