"""Grouped Pallas FFN kernel vs the batched XLA path (interpreter mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import Activation, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.expert import (
    capacity_buffer_ffn_pallas,
    expert_ffn_dense,
    grouped_ffn,
)

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _params_x(cfg, c, seed=0):
    key = jax.random.PRNGKey(seed)
    params = init_moe_params(key, cfg)
    xs = jax.random.normal(
        jax.random.PRNGKey(seed + 1),
        (cfg.num_experts, c, cfg.hidden_size), jnp.float32,
    )
    return params, xs


@pytest.mark.parametrize("cfg,cap", [
    (MoEConfig(num_experts=4, hidden_size=128, intermediate_size=256, **F32),
     128),
    (MoEConfig(num_experts=4, hidden_size=128, intermediate_size=512,
               hidden_act=Activation.RELU, **F32), 64),
    (MoEConfig(num_experts=2, hidden_size=256, intermediate_size=1024,
               gated_ffn=True, hidden_act=Activation.SILU, **F32), 128),
], ids=["gelu", "relu_smallcap", "gated_silu"])
def test_capacity_buffer_matches_dense(cfg, cap):
    params, xs = _params_x(cfg, cap)
    want = expert_ffn_dense(xs, params, cfg)
    got = capacity_buffer_ffn_pallas(xs, params, cfg, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_grouped_ffn_respects_tile_gid():
    """Row tiles must each use exactly their own expert's weights."""
    cfg = MoEConfig(num_experts=4, hidden_size=128, intermediate_size=256,
                    **F32)
    params, _ = _params_x(cfg, 8)
    bm = 8
    # tiles assigned to experts in scrambled order, incl. repeats
    tile_gid = jnp.array([2, 0, 3, 3, 1, 0], dtype=jnp.int32)
    x = jax.random.normal(jax.random.PRNGKey(7), (6 * bm, 128), jnp.float32)
    got = grouped_ffn(
        x, tile_gid, params["w_up"], params["b_up"], params["w_down"],
        params["b_down"], act_name=cfg.hidden_act, block_m=bm,
        block_i=128, interpret=True,
    )
    # oracle: per-tile dense FFN with that tile's expert
    for t in range(6):
        e = int(tile_gid[t])
        xt = x[t * bm:(t + 1) * bm]
        up = xt @ params["w_up"][e] + params["b_up"][e]
        want = jax.nn.gelu(up) @ params["w_down"][e] + params["b_down"][e]
        np.testing.assert_allclose(
            np.asarray(got[t * bm:(t + 1) * bm]), np.asarray(want),
            rtol=2e-4, atol=2e-4,
        )
