"""Grouped Pallas FFN kernel vs the batched XLA path (interpreter mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import Activation, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.expert import (
    capacity_buffer_ffn_ad,
    capacity_buffer_ffn_pallas,
    expert_ffn_dense,
    grouped_ffn,
    grouped_matmul,
    tgmm,
)

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _params_x(cfg, c, seed=0):
    key = jax.random.PRNGKey(seed)
    params = init_moe_params(key, cfg)
    xs = jax.random.normal(
        jax.random.PRNGKey(seed + 1),
        (cfg.num_experts, c, cfg.hidden_size), jnp.float32,
    )
    return params, xs


@pytest.mark.parametrize("cfg,cap", [
    (MoEConfig(num_experts=4, hidden_size=128, intermediate_size=256, **F32),
     128),
    (MoEConfig(num_experts=4, hidden_size=128, intermediate_size=512,
               hidden_act=Activation.RELU, **F32), 64),
    (MoEConfig(num_experts=2, hidden_size=256, intermediate_size=1024,
               gated_ffn=True, hidden_act=Activation.SILU, **F32), 128),
], ids=["gelu", "relu_smallcap", "gated_silu"])
def test_capacity_buffer_matches_dense(cfg, cap):
    params, xs = _params_x(cfg, cap)
    want = expert_ffn_dense(xs, params, cfg)
    got = capacity_buffer_ffn_pallas(xs, params, cfg, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_grouped_matmul_and_tgmm_match_einsum():
    """The backward kernels against XLA oracles: grouped matmul (plain and
    transposed weights) and the transposed grouped GEMM (dW)."""
    e, t, k, n, bm = 3, 6 * 16, 128, 256, 16
    kx = jax.random.PRNGKey(0)
    x = jax.random.normal(kx, (t, k), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (e, k, n), jnp.float32)
    wt = jax.random.normal(jax.random.PRNGKey(2), (e, n, k), jnp.float32)
    gid = jnp.array([0, 0, 1, 2, 2, 2], jnp.int32)  # nondecreasing
    row_e = jnp.repeat(gid, bm)

    got = grouped_matmul(x, gid, w, block_m=bm, interpret=True)
    want = jnp.einsum("tk,tkn->tn", x, w[row_e])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    got_t = grouped_matmul(x, gid, wt, transpose_w=True, block_m=bm,
                           interpret=True)
    want_t = jnp.einsum("tk,tnk->tn", x, wt[row_e])
    np.testing.assert_allclose(np.asarray(got_t), np.asarray(want_t),
                               rtol=2e-4, atol=2e-4)

    dy = jax.random.normal(jax.random.PRNGKey(3), (t, n), jnp.float32)
    got_w = tgmm(x, dy, gid, e, block_m=bm, interpret=True)
    oh = jax.nn.one_hot(row_e, e, dtype=jnp.float32)
    want_w = jnp.einsum("tk,tn,te->ekn", x, dy, oh)
    np.testing.assert_allclose(np.asarray(got_w), np.asarray(want_w),
                               rtol=2e-4, atol=2e-4)


def test_tgmm_zero_token_expert_gets_zero_grad():
    """An expert absent from tile_gid must get exactly-zero dW, not the
    uninitialized garbage of its never-visited output blocks."""
    e, bm = 3, 16
    gid = jnp.array([0, 0, 2], jnp.int32)  # expert 1 has no tiles
    x = jax.random.normal(jax.random.PRNGKey(0), (3 * bm, 64), jnp.float32)
    dy = jax.random.normal(jax.random.PRNGKey(1), (3 * bm, 128), jnp.float32)
    dw = tgmm(x, dy, gid, e, block_m=bm, interpret=True)
    assert np.isfinite(np.asarray(dw)).all()
    assert (np.asarray(dw[1]) == 0).all()


def test_backward_handles_non_512_multiple_dims():
    """H or I not a multiple of 512 (e.g. 768) must train, not crash: the
    backward kernels fall back to a dividing chunk size."""
    cfg = MoEConfig(num_experts=2, hidden_size=192, intermediate_size=320,
                    **F32)
    params, xs = _params_x(cfg, 64)

    def loss(xs, p):
        return (capacity_buffer_ffn_ad(xs, p, cfg, interpret=True)
                .astype(jnp.float32) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1))(xs, params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.parametrize("cfg,cap", [
    (MoEConfig(num_experts=4, hidden_size=128, intermediate_size=256, **F32),
     64),
    (MoEConfig(num_experts=2, hidden_size=128, intermediate_size=512,
               gated_ffn=True, hidden_act=Activation.SILU, **F32), 64),
], ids=["gelu", "gated_silu"])
def test_fused_backward_matches_xla_grads(cfg, cap):
    """The Pallas backward (grouped_matmul/tgmm with saved residuals) must
    produce the same gradients as autodiff through the dense XLA FFN."""
    params, xs = _params_x(cfg, cap)

    def loss_pallas(xs, p):
        y = capacity_buffer_ffn_ad(xs, p, cfg, interpret=True)
        return (y.astype(jnp.float32) ** 2).sum()

    def loss_dense(xs, p):
        y = expert_ffn_dense(xs, p, cfg)
        return (y.astype(jnp.float32) ** 2).sum()

    gp = jax.grad(loss_pallas, argnums=(0, 1))(xs, params)
    gd = jax.grad(loss_dense, argnums=(0, 1))(xs, params)
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(gd[0]),
                               rtol=5e-3, atol=5e-3)
    for k in gd[1]:
        if k.startswith("shared"):
            continue
        np.testing.assert_allclose(
            np.asarray(gp[1][k]), np.asarray(gd[1][k]),
            rtol=5e-3, atol=5e-3, err_msg=k,
        )


def test_grouped_ffn_respects_tile_gid():
    """Row tiles must each use exactly their own expert's weights."""
    cfg = MoEConfig(num_experts=4, hidden_size=128, intermediate_size=256,
                    **F32)
    params, _ = _params_x(cfg, 8)
    bm = 8
    # tiles assigned to experts in scrambled order, incl. repeats
    tile_gid = jnp.array([2, 0, 3, 3, 1, 0], dtype=jnp.int32)
    x = jax.random.normal(jax.random.PRNGKey(7), (6 * bm, 128), jnp.float32)
    got = grouped_ffn(
        x, tile_gid, params["w_up"], params["b_up"], params["w_down"],
        params["b_down"], act_name=cfg.hidden_act, block_m=bm,
        block_i=128, interpret=True,
    )
    # oracle: per-tile dense FFN with that tile's expert
    for t in range(6):
        e = int(tile_gid[t])
        xt = x[t * bm:(t + 1) * bm]
        up = xt @ params["w_up"][e] + params["b_up"][e]
        want = jax.nn.gelu(up) @ params["w_down"][e] + params["b_down"][e]
        np.testing.assert_allclose(
            np.asarray(got[t * bm:(t + 1) * bm]), np.asarray(want),
            rtol=2e-4, atol=2e-4,
        )
