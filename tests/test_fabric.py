"""Disaggregated serving fabric (flashmoe_tpu/fabric/): EP-sharded
decode replicas behind a JSQ+affinity router, Decider-split
prefill/decode pools, and the DCN-priced KV-page handoff.

The headline drill is the ISSUE acceptance: a mocked 2-pool x
2-replica fabric (``FLASHMOE_MOCK_FABRIC=2`` on the virtual 8-device
CPU mesh) sustaining 8 concurrent requests with at least one KV
handoff and at least one eviction/re-prefill cycle, token-bit-equal
to the single-pool :class:`ServingEngine` on the same trace, with a
live mid-drill ``/metrics`` scrape carrying per-replica TTFT/TPOT
sketches.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.fabric import (
    KVHandoff, ReplicaRouter, ServingFabric, decode_kv_run,
    encode_kv_run, fabric_world,
)
from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC, _mock_fabric
from flashmoe_tpu.models.transformer import init_params
from flashmoe_tpu.serving.engine import (
    Request, ServeConfig, ServingEngine,
)
from flashmoe_tpu.serving.loadgen import (
    build_requests, merge_traces, split_requests, tiny_config,
)
from flashmoe_tpu.utils.telemetry import Metrics

CFG = tiny_config()


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def prompts():
    return jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0,
                              CFG.vocab_size)


def _requests(prompts, n, max_new=6, **kw):
    return [Request(rid=i, prompt=tuple(int(t) for t in prompts[i]),
                    max_new_tokens=max_new, **kw) for i in range(n)]


# ----------------------------------------------------------------------
# Mocked topology (FLASHMOE_MOCK_FABRIC)
# ----------------------------------------------------------------------

def test_mock_fabric_env_parse_hardened(monkeypatch):
    """Malformed mocks are configuration errors naming the world size
    (mirroring FLASHMOE_MOCK_SLICES) — never a silent single-replica
    fallback."""
    monkeypatch.delenv(ENV_MOCK_FABRIC, raising=False)
    assert _mock_fabric(8) is None
    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")
    assert _mock_fabric(8) == 2
    assert fabric_world(8) == (2, 4)
    monkeypatch.setenv(ENV_MOCK_FABRIC, "1")
    assert _mock_fabric(8) is None          # 1 = no blocking
    for bad in ("x", "2.5", ""):
        monkeypatch.setenv(ENV_MOCK_FABRIC, bad)
        if bad == "":
            assert _mock_fabric(8) is None  # empty = unset
            continue
        with pytest.raises(ValueError, match="8 devices"):
            _mock_fabric(8)
    monkeypatch.setenv(ENV_MOCK_FABRIC, "0")
    with pytest.raises(ValueError, match=">= 1"):
        _mock_fabric(8)
    monkeypatch.setenv(ENV_MOCK_FABRIC, "-2")
    with pytest.raises(ValueError, match=">= 1"):
        _mock_fabric(8)
    monkeypatch.setenv(ENV_MOCK_FABRIC, "3")
    with pytest.raises(ValueError, match="does not divide"):
        _mock_fabric(8)


def test_mock_fabric_single_device_colocates(monkeypatch):
    """On a 1-device world any replica count co-locates (replicas are
    full engines, not device partitions) — the bare-CPU bench sweep's
    CI story."""
    monkeypatch.setenv(ENV_MOCK_FABRIC, "4")
    assert _mock_fabric(1) == 4
    assert fabric_world(1) == (4, 1)
    with pytest.raises(ValueError, match=">= 1 device"):
        fabric_world(0)


# ----------------------------------------------------------------------
# KV-page handoff codec
# ----------------------------------------------------------------------

def _kv_run(seed, l=2, nkv=2, t=16, d=8):
    k = jax.random.normal(jax.random.PRNGKey(seed), (l, nkv, t, d),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(seed + 1),
                          (l, nkv, t, d), jnp.float32)
    return k, v


def test_kv_codec_wire_off_is_exact_passthrough():
    """wire=None returns the arrays untouched — no cast, no sidecar:
    the property that makes the fabric drill bit-equal by
    construction."""
    k, v = _kv_run(0)
    p = encode_kv_run(k, v, 8, None)
    assert p.wire == "off" and p.pages == 2
    assert p.k_qscale is None and p.v_qscale is None
    ko, vo = decode_kv_run(p, jnp.float32)
    assert ko is k and vo is v              # same objects, zero copies


def test_kv_codec_fp8_roundtrip_zero_preserving():
    """The e4m3 page wire round-trips within fp8 error, preserves
    exact zeros (padded page tails stay zero), and carries one f32
    scale per (layer, page) row."""
    k, v = _kv_run(2)
    k = k.at[:, :, 12:, :].set(0.0)         # padded tail
    p = encode_kv_run(k, v, 8, jnp.float8_e4m3fn)
    assert p.wire == "e4m3" and p.pages == 2
    assert p.k_qscale is not None and p.v_qscale is not None
    assert p.k_qscale.shape[0] == 2 * 2     # L * n_pages rows
    assert p.payload_bytes < int(k.nbytes) + int(v.nbytes)
    ko, vo = decode_kv_run(p, jnp.float32)
    np.testing.assert_allclose(np.asarray(ko), np.asarray(k),
                               rtol=0.08, atol=0.08)
    np.testing.assert_array_equal(
        np.asarray(ko[:, :, 12:, :]), 0.0)  # zeros exact
    np.testing.assert_allclose(np.asarray(vo), np.asarray(v),
                               rtol=0.08, atol=0.08)


def test_kv_codec_rejects_partial_pages():
    k, v = _kv_run(4, t=12)                 # 12 % 8 != 0
    with pytest.raises(ValueError, match="whole pages"):
        encode_kv_run(k, v, 8, jnp.float8_e4m3fn)


def test_kv_handoff_prices_and_records(params):
    """Every handoff is DCN-priced through planner.model.kv_handoff_ms
    and recorded as a fabric.handoff decision with the overlap
    verdict."""
    mx = Metrics()
    ho = KVHandoff(params, CFG, 8, metrics_obj=mx,
                   decode_step_ms=1e9)      # everything overlaps
    prompt = jnp.zeros((1, 8), jnp.int32)
    logits, k, v = ho.prefill(prompt, 8, replica=1, rid=7)
    assert ho.count == 1 and ho.bytes_moved > 0
    d = [r for r in mx.decisions if r["decision"] == "fabric.handoff"]
    assert len(d) == 1
    assert d[0]["replica"] == 1 and d[0]["rid"] == 7
    assert d[0]["wire"] == "off"
    assert d[0]["modeled_dcn_ms"] > 0
    assert d[0]["overlapped"] is True
    snap = ho.snapshot()
    assert snap["handoffs"] == 1 and snap["wire"] == "off"


# ----------------------------------------------------------------------
# Replica router
# ----------------------------------------------------------------------

def _health(depth, ok=True):
    def fn():
        if ok is None:
            raise RuntimeError("replica down")
        return {"queue_depth": depth, "active_requests": 0, "ok": ok}
    return fn


def test_router_jsq_and_tiebreak():
    mx = Metrics()
    r = ReplicaRouter([_health(3), _health(1), _health(1)],
                      metrics_obj=mx, affinity=False)
    assert r.route(rid=0) == 1              # shortest queue, lowest id
    d = mx.decisions[-1]
    assert d["decision"] == "fabric.route" and d["policy"] == "jsq"
    assert d["queue_depths"] == [3, 1, 1]


def test_router_session_affinity_and_spill():
    import zlib

    mx = Metrics()
    r = ReplicaRouter([_health(9), _health(0)], metrics_obj=mx)
    want = zlib.crc32(b"alice") % 2
    # affinity wins even against a longer queue
    assert r.route(rid=0, session="alice") == want
    assert mx.decisions[-1]["policy"] == "affinity"
    # a draining preferred replica spills to JSQ
    r.drain(want)
    got = r.route(rid=1, session="alice")
    assert got == 1 - want
    assert mx.decisions[-1]["policy"] == "jsq_spill"
    r.undrain(want)
    assert r.route(rid=2, session="alice") == want


def test_router_unhealthy_and_all_draining_fallback():
    mx = Metrics()
    r = ReplicaRouter([_health(0, ok=None), _health(5)],
                      metrics_obj=mx, affinity=False)
    assert r.route(rid=0) == 1              # raising probe = unhealthy
    # every replica draining: fall back to the full rotation rather
    # than dropping the request
    r2 = ReplicaRouter([_health(2), _health(1)], metrics_obj=mx,
                       affinity=False)
    r2.drain(0), r2.drain(1)
    assert r2.route(rid=0) == 1
    with pytest.raises(ValueError, match="out of range"):
        r2.drain(5)
    with pytest.raises(ValueError, match=">= 1 replica"):
        ReplicaRouter([])


# ----------------------------------------------------------------------
# Controller replica morph (PR 9 discipline on the rotation)
# ----------------------------------------------------------------------

def _controller(**kw):
    from flashmoe_tpu.runtime.controller import (
        ControllerConfig, RuntimeController,
    )

    mx = Metrics()
    ccfg = ControllerConfig(enable_replica_morph=True, debounce_steps=3,
                            cooldown_steps=8, replica_morph_budget=2,
                            **kw)
    return RuntimeController(CFG, ccfg, metrics=mx), mx


def test_replica_morph_hysteresis_band_validated():
    from flashmoe_tpu.runtime.controller import ControllerConfig

    with pytest.raises(ValueError, match="replica_queue_low"):
        ControllerConfig(replica_queue_low=4.0, replica_queue_high=4.0)


def test_replica_morph_debounce_drain_and_undrain():
    """Sustained idleness drains the highest-id rotating replica (never
    below one); sustained pressure returns the lowest-id drained one;
    both debounce on consecutive observations and burn the shared
    budget under a cooldown window."""
    ctl, mx = _controller()
    step = 0
    # two idle observations then a busy one: debounce resets, no act
    for d in ([0, 0], [0, 0], [9, 9]):
        step += 1
        ctl.observe_fabric(step, d)
        assert ctl.maybe_morph_replicas(step) is None
    # three consecutive idle steps -> drain replica 1 (max rotating)
    for _ in range(3):
        step += 1
        ctl.observe_fabric(step, [0, 0])
    act = ctl.maybe_morph_replicas(step, draining=())
    assert act is not None and act.kind == "drain" and act.replica == 1
    recs = [r for r in mx.decisions
            if r["decision"] == "controller.replica_morph"]
    assert recs and recs[-1]["trigger"] == "queue_low"
    # cooldown window suppresses (one controller.cooldown record)
    for _ in range(3):
        step += 1
        ctl.observe_fabric(step, [0, 0])
    assert ctl.maybe_morph_replicas(step, draining=(1,)) is None
    cools = [r for r in mx.decisions
             if r["decision"] == "controller.cooldown"
             and r["trigger"] == "replica"]
    assert len(cools) == 1
    # past cooldown, sustained pressure undrains the drained replica
    step += 10
    for _ in range(3):
        step += 1
        ctl.observe_fabric(step, [9, 9])
    act = ctl.maybe_morph_replicas(step, draining=(1,))
    assert act.kind == "undrain" and act.replica == 1
    # budget (2) is spent: a third sustained trigger is inert
    step += 10
    for _ in range(3):
        step += 1
        ctl.observe_fabric(step, [0, 0])
    assert ctl.maybe_morph_replicas(step, draining=()) is None
    assert ctl.snapshot()["budgets"]["replica_morph"] == 0


def test_replica_morph_never_drains_last_replica():
    ctl, _ = _controller()
    for s in range(1, 4):
        ctl.observe_fabric(s, [0, 0])
    assert ctl.maybe_morph_replicas(3, draining=(1,)) is None


def test_replica_morph_budget_survives_restart():
    ctl, _ = _controller()
    ctl.replica_morphs_used = 2
    state = ctl.state_dict()
    ctl2, _ = _controller()
    ctl2.replica_morphs_used = 1
    ctl2.load_state_dict(state)
    assert ctl2.replica_morphs_used == 2    # monotonic max


# ----------------------------------------------------------------------
# Per-replica trace split (loadgen)
# ----------------------------------------------------------------------

def test_split_requests_deterministic_disjoint():
    kw = dict(vocab=CFG.vocab_size, prompt_len=8, max_new=4, seed=7,
              arrival_every=2)
    a = split_requests(8, replicas=3, **kw)
    b = split_requests(8, replicas=3, **kw)
    assert a == b                           # reproducible
    assert [len(r) for r, _ in a] == [3, 3, 2]   # remainder to low ids
    rids = [q.rid for reqs, _ in a for q in reqs]
    assert len(set(rids)) == 8              # globally unique
    assert all(q.rid % 3 == r for r, (reqs, _) in enumerate(a)
               for q in reqs)
    # per-replica seeds diverge: different prompts across replicas
    assert a[0][0][0].prompt != a[1][0][0].prompt
    with pytest.raises(ValueError, match="replicas"):
        split_requests(4, replicas=0, **kw)


def test_merge_traces_arrival_ordered():
    kw = dict(vocab=CFG.vocab_size, prompt_len=8, max_new=4, seed=7,
              arrival_every=2)
    reqs, arrivals = merge_traces(split_requests(8, replicas=2, **kw))
    assert len(reqs) == 8
    assert arrivals == sorted(arrivals)
    # ties break on rid: deterministic merge
    for (a1, q1), (a2, q2) in zip(zip(arrivals, reqs),
                                  list(zip(arrivals, reqs))[1:]):
        assert (a1, q1.rid) < (a2, q2.rid)


def test_per_replica_shards_merge_in_observe(tmp_path):
    """Each replica's decision dump is one host shard; observe --merge
    reads the union as ONE fabric (satellite: mergeable artifacts)."""
    from flashmoe_tpu.observe import merge_report

    for r in range(2):
        p = tmp_path / f"telemetry.r{r}.jsonl"
        with open(p, "w") as f:
            for i in range(3):
                f.write(json.dumps({
                    "decision": "fabric.route", "rid": i * 2 + r,
                    "replica": r, "policy": "affinity",
                    "queue_depths": [0, 0], "draining": []}) + "\n")
    rep = merge_report([str(tmp_path / "telemetry.r0.jsonl"),
                        str(tmp_path / "telemetry.r1.jsonl")])
    assert set(rep["hosts"]) == {"r0", "r1"}
    assert rep["records"] == 6


def test_serving_report_surfaces_fabric_decisions():
    """observe --serving folds serve.pools / fabric.route /
    fabric.handoff into the serving story."""
    from flashmoe_tpu.observe import render_serving_text, serving_report

    recs = [
        {"decision": "serve.pools", "prefill_devices": [0, 1],
         "decode_devices": [2, 3], "prefill_ms": 1.5, "decode_ms": 0.4,
         "prefill_mapping": "single", "decode_mapping": "single",
         "decode_quant": "int8", "kv_wire": "e4m3"},
        {"decision": "fabric.route", "replica": 0, "policy": "affinity",
         "queue_depths": [0, 0], "draining": []},
        {"decision": "fabric.route", "replica": 1, "policy": "jsq",
         "queue_depths": [2, 0], "draining": []},
        {"decision": "fabric.handoff", "rid": 0, "replica": 0,
         "pages": 2, "wire": "e4m3", "payload_kb": 4.0,
         "modeled_dcn_ms": 0.02, "overlapped": True},
        {"decision": "serve.retire", "rid": 0, "ttft_ms": 5.0,
         "tpot_ms": 1.0},
    ]
    rep = serving_report(recs)
    assert rep["pools"]["decode_quant"] == "int8"
    assert rep["fabric_route"]["placements"] == {"0": 1, "1": 1}
    assert rep["fabric_route"]["policies"] == {"affinity": 1, "jsq": 1}
    assert rep["fabric_handoff"]["count"] == 1
    assert rep["fabric_handoff"]["overlapped_frac"] == 1.0
    text = render_serving_text(rep)
    assert "pools:" in text and "fabric router:" in text
    assert "kv handoff:" in text and "wire e4m3" in text


# ----------------------------------------------------------------------
# Chunked prefill x eviction (single-pool engine)
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_chunked_prefill_with_eviction_bit_equal(params):
    """A 24-token prompt admitted in 8-token chunks under page
    pressure: requests evict and re-prefill (again chunked) and the
    token streams stay bit-equal to the unchunked engine."""
    long_prompts = jax.random.randint(jax.random.PRNGKey(5), (4, 24),
                                      0, CFG.vocab_size)
    reqs = [Request(rid=i,
                    prompt=tuple(int(t) for t in long_prompts[i]),
                    max_new_tokens=10) for i in range(4)]
    base = ServeConfig(max_batch=4, page_size=8, num_pages=14,
                       max_pages_per_slot=5, ctx_bucket_pages=1,
                       prompt_bucket=8)
    import dataclasses

    mx = Metrics()
    eng = ServingEngine(params, CFG,
                        dataclasses.replace(base, prefill_chunk=8),
                        metrics_obj=mx)
    out = eng.run(reqs)
    s = eng.summary()
    assert s["completed"] == 4
    assert s["evictions"] > 0               # re-prefill cycle, chunked
    plain = ServingEngine(params, CFG, base, metrics_obj=Metrics())
    out_plain = plain.run([Request(
        rid=i, prompt=tuple(int(t) for t in long_prompts[i]),
        max_new_tokens=10) for i in range(4)])
    for i in range(4):
        np.testing.assert_array_equal(np.asarray(out[i]),
                                      np.asarray(out_plain[i]))


# ----------------------------------------------------------------------
# The acceptance drill
# ----------------------------------------------------------------------

def test_fabric_drill_2x2_bit_equal_with_live_scrape(params, prompts,
                                                     monkeypatch):
    """ISSUE acceptance: mocked 2-pool x 2-replica fabric, 8 concurrent
    requests, >=1 KV handoff and >=1 eviction/re-prefill, outputs
    token-bit-equal to the single-pool engine, and a LIVE mid-drill
    /metrics scrape with per-replica TTFT/TPOT sketches."""
    import urllib.request

    serve = ServeConfig(max_batch=4, page_size=8, num_pages=8,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8)
    reqs = _requests(prompts, 8, max_new=10)
    arrivals = [0, 0, 0, 0, 1, 1, 2, 3]

    # single-pool baseline
    base = ServingEngine(params, CFG, serve, metrics_obj=Metrics())
    out_base = base.run(_requests(prompts, 8, max_new=10), arrivals)

    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")
    mx = Metrics()
    fab = ServingFabric(params, CFG, serve, metrics_obj=mx,
                        telemetry_port=0)
    try:
        assert fab.n_replicas == 2
        assert fab.pool_plan is not None    # 2 pools formed (8 devices)
        for req, arr in zip(reqs, arrivals):
            fab.submit(req, arr)
        # drive until a retirement seeds a replica sketch, then scrape
        # while work is still in flight
        while fab.pending() and not any(
                k.endswith(".ttft_ms") and ".r" in k
                for k in mx.sketches):
            fab.step()
        assert fab.pending()
        url = f"http://127.0.0.1:{fab.telemetry.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = r.read().decode()
        assert 'flashmoe_serve_r0_ttft_ms{quantile="' in body \
            or 'flashmoe_serve_r1_ttft_ms{quantile="' in body
        while fab.pending():
            fab.step()
        out = {rid: toks for rid, toks in
               (pair for e in fab.engines
                for pair in e.outputs.items())}
        s = fab.summary()
    finally:
        fab.close()

    assert s["handoffs"] >= 1               # every prefill crossed DCN
    assert sum(e["evictions"] for e in s["engines"]) >= 1
    assert sum(e["completed"] for e in s["engines"]) == 8
    assert sorted(s["routed"]) and sum(s["routed"]) == 8
    # token-bit-equal to the single-pool engine
    for i in range(8):
        np.testing.assert_array_equal(np.asarray(out[i]),
                                      np.asarray(out_base[i]))
    # the decision plane told the story
    routes = [d for d in mx.decisions if d["decision"] == "fabric.route"]
    handoffs = [d for d in mx.decisions
                if d["decision"] == "fabric.handoff"]
    assert len(routes) == 8
    assert len(handoffs) == s["handoffs"]
    assert all(d["modeled_dcn_ms"] > 0 for d in handoffs)
    # /vars carries pools + handoff + router + per-replica plans
    v = fab._vars_snapshot()
    assert v["replicas"] == 2 and v["pools"] is not None
    assert v["handoff"]["handoffs"] == s["handoffs"]
    assert len(v["engines"]) == 2


def test_fabric_controller_drains_idle_replica(params, prompts,
                                               monkeypatch):
    """An armed controller watching an idling fabric consolidates: the
    queue_low trigger drains the highest-id replica through the
    router (controller.replica_morph recorded, rotation shrinks)."""
    from flashmoe_tpu.runtime.controller import (
        ControllerConfig, RuntimeController,
    )

    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")
    mx = Metrics()
    ctl = RuntimeController(
        CFG,
        ControllerConfig(enable_replica_morph=True, debounce_steps=2,
                         cooldown_steps=4, replica_morph_budget=1,
                         replica_queue_low=3.0, replica_queue_high=9.0),
        metrics=mx)
    serve = ServeConfig(max_batch=4, page_size=8, num_pages=32,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8)
    fab = ServingFabric(params, CFG, serve, metrics_obj=mx,
                        controller=ctl)
    out = fab.run(_requests(prompts, 2, max_new=8), [0, 0])
    assert len(out) == 2
    morphs = [d for d in mx.decisions
              if d["decision"] == "controller.replica_morph"]
    assert morphs and morphs[0]["kind"] == "drain"
    assert fab.router.draining() == (morphs[0]["replica"],)


# ----------------------------------------------------------------------
# Golden fabric dimension
# ----------------------------------------------------------------------

def test_fabric_golden_gated():
    """The modeled KV-handoff cost is CI-gated next to the plans it
    must hide under: recompute the golden fabric section and require
    the fp8 page wire to flip at least one overlap verdict."""
    from flashmoe_tpu.planner.golden import GOLDEN_PATH, golden_snapshot

    with open(GOLDEN_PATH) as f:
        frozen = json.load(f)
    live = golden_snapshot()
    assert live["fabric"] == frozen["fabric"], (
        "fabric golden points moved; if intentional regenerate with "
        "python -m flashmoe_tpu.planner --regen-golden")
    pts = [g for gens in frozen["fabric"].values()
           for g in gens.values()]
    assert all(p["fp8_saves"] for p in pts)   # fp8 wire always cheaper
    assert any(p["wires"]["e4m3"]["overlapped"]
               and not p["wires"]["off"]["overlapped"]
               for p in pts), (
        "no golden config where the fp8 page wire flips the handoff "
        "under the decode step — the fabric pricing lost its teeth")
