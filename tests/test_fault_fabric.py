"""Fault-tolerant serving fabric (PR 18): the serving-side recovery
ladder.

Fast lanes drill each mechanism directly — the CRC'd failable handoff
transport (tamper => exactly one retry, bit-equal payload), silent
replica crash => probe detection => front-of-queue migration with
token-bit-equal streams, hysteretic brownout shedding, and the
lease-replicated front-door cluster's epoch-bumped failover — all on
mocked ``FLASHMOE_MOCK_FABRIC`` worlds stepping a
:class:`VirtualClock` (trace validation needs virtual time: sibling
jit compiles hole a wall-clock timeline).  PR 19 adds the
cross-process arms: the REAL tcp socket wire (cut mid-stream =>
reconnect + retry, bit-equal payload), the sub-step heartbeat
watchdog (a mid-step hang the probes cannot see), and the external
fenced lease store (tests/test_leasestore.py owns the store itself).
The slow lane runs the eight serving chaos-matrix drills end to end
(``@pytest.mark.slow`` per the lint's tier-1 budget guard).
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from flashmoe_tpu.chaos import EXPECTED_TIER, FAULTS, FaultPlan
from flashmoe_tpu.fabric import (
    FrontDoor, FrontDoorCluster, HandoffTransport, HandoffTransportError,
    ServingFabric, VirtualClock,
)
from flashmoe_tpu.fabric.handoff import encode_kv_run
from flashmoe_tpu.fabric.router import ReplicaRouter
from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
from flashmoe_tpu.fabric.transport import (
    encode_frames, verify_frames,
)
from flashmoe_tpu.models.transformer import init_params
from flashmoe_tpu.runtime.controller import BrownoutConfig
from flashmoe_tpu.serving.engine import ServeConfig, ServingEngine
from flashmoe_tpu.serving.loadgen import build_requests, tiny_config
from flashmoe_tpu.utils.integrity import crc32_bytes, crc32_pages
from flashmoe_tpu.utils.telemetry import DECISION_NAMES, Metrics

CFG = tiny_config()
SERVE = ServeConfig(max_batch=2, page_size=8, num_pages=64,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8)

SERVING_FAULTS = ("replica_crash", "handoff_corrupt",
                  "handoff_timeout", "frontdoor_loss",
                  "net_partition", "lease_split_brain",
                  "replica_stall", "lease_torn_write")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def trace():
    return build_requests(6, vocab=CFG.vocab_size, prompt_len=8,
                          max_new=4, seed=0, arrival_every=1)


@pytest.fixture(scope="module")
def baseline(params, trace):
    """The gold standard: the same seeded trace through one
    uninterrupted single-pool engine."""
    reqs, arrivals = trace
    eng = ServingEngine(params, CFG, SERVE, metrics_obj=Metrics())
    out = eng.run(reqs, arrivals)
    eng.close()
    return out


@pytest.fixture()
def mock2(monkeypatch):
    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")


def _assert_bit_equal(outputs, baseline):
    assert sorted(outputs) == sorted(baseline)
    for rid in baseline:
        assert outputs[rid] == baseline[rid], f"rid {rid} diverged"


# ----------------------------------------------------------------------
# CRC helpers + wire frames (pure unit)
# ----------------------------------------------------------------------

def test_crc32_pages_splits_and_detects_flips():
    data = bytes(range(251)) * 4
    crcs = crc32_pages(data, 4)
    assert len(crcs) == 4
    # whole-buffer checksum is NOT the concatenation trivially, but a
    # one-byte flip must change exactly the page that holds it
    flipped = bytearray(data)
    flipped[300] ^= 0xFF
    crcs2 = crc32_pages(bytes(flipped), 4)
    diff = [i for i, (a, b) in enumerate(zip(crcs, crcs2)) if a != b]
    assert diff == [300 // (len(data) // 4)]
    # degenerate shapes stay defined
    assert crc32_pages(b"", 3) == (crc32_bytes(b""),) * 3
    assert len(crc32_pages(data, 1)) == 1


def test_wire_frames_roundtrip_and_verify():
    k = jax.random.normal(jax.random.PRNGKey(2), (2, 2, 16, 4))
    v = jax.random.normal(jax.random.PRNGKey(3), (2, 2, 16, 4))
    payload = encode_kv_run(np.asarray(k), np.asarray(v), 8, None)
    frames = encode_frames(payload)
    assert verify_frames(frames) == []
    # stamp garbage into the k frame: verify names (field, page)
    bad = dataclasses.replace(
        frames["k"], buf=b"\x00" * len(frames["k"].buf))
    assert frames["k"].buf != bad.buf
    broken = dict(frames, k=bad)
    named = verify_frames(broken)
    assert named and all(f == "k" for f, _ in named)


# ----------------------------------------------------------------------
# HandoffTransport (no engine)
# ----------------------------------------------------------------------

def _payload(seed=4):
    k = np.asarray(jax.random.normal(jax.random.PRNGKey(seed),
                                     (2, 2, 16, 4)))
    v = np.asarray(jax.random.normal(jax.random.PRNGKey(seed + 1),
                                     (2, 2, 16, 4)))
    return encode_kv_run(k, v, 8, None)


def test_transport_clean_send_is_bit_identical():
    mx = Metrics()
    t = HandoffTransport(metrics_obj=mx)
    p = _payload()
    res = t.send(p, modeled_ms=0.5, rid=0)
    assert res.attempts == 1 and res.retries == 0
    assert res.retry_ms == 0.0
    np.testing.assert_array_equal(np.asarray(res.payload.k),
                                  np.asarray(p.k))
    np.testing.assert_array_equal(np.asarray(res.payload.v),
                                  np.asarray(p.v))
    assert t.snapshot()["retries_total"] == 0
    assert not [d for d in mx.decisions
                if d["decision"] == "fabric.handoff_retry"]


def test_transport_tamper_trips_crc_and_retries_exactly_once():
    mx = Metrics()
    t = HandoffTransport(
        metrics_obj=mx,
        tamper_fn=lambda index, attempt: index == 0 and attempt == 1)
    p = _payload()
    res = t.send(p, modeled_ms=0.5, rid=7, replica=1)
    assert res.attempts == 2 and res.retries == 1
    assert res.corrupt_pages > 0 and res.timeouts == 0
    assert res.retry_ms > 0.5  # wasted wire + backoff
    np.testing.assert_array_equal(np.asarray(res.payload.k),
                                  np.asarray(p.k))
    corrupt = [d for d in mx.decisions
               if d["decision"] == "fabric.handoff_corrupt"]
    retry = [d for d in mx.decisions
             if d["decision"] == "fabric.handoff_retry"]
    assert len(corrupt) == 1 and corrupt[0]["bad_page_count"] > 0
    assert len(retry) == 1 and retry[0]["reason"] == "corrupt"
    assert retry[0]["rid"] == 7 and retry[0]["replica"] == 1
    # the second transfer is clean: fault fired on transfer 0 only
    res2 = t.send(_payload(8), modeled_ms=0.5, rid=8)
    assert res2.retries == 0


def test_transport_timeout_plan_and_budget_exhaustion():
    mx = Metrics()
    t = HandoffTransport(
        metrics_obj=mx, max_retries=2, timeout_ms=10.0, backoff_ms=2.0,
        plan=FaultPlan("handoff_timeout", step=0, duration=1))
    res = t.send(_payload(), modeled_ms=0.5)
    assert res.timeouts == 1 and res.retries == 1
    assert res.retry_ms == pytest.approx(10.0 + 2.0)
    # a persistent fault (once=False) exhausts the bounded budget
    t2 = HandoffTransport(
        metrics_obj=mx, max_retries=2,
        plan=FaultPlan("handoff_timeout", step=0, duration=1,
                       once=False))
    with pytest.raises(HandoffTransportError, match="retry budget"):
        t2.send(_payload())
    assert t2.timeout_total == 3  # 1 first attempt + 2 retries


def test_transport_backoff_caps_and_validates():
    t = HandoffTransport(backoff_ms=5.0, backoff_cap_ms=12.0)
    assert t._backoff(1) == 5.0
    assert t._backoff(2) == 10.0
    assert t._backoff(3) == 12.0  # capped, not 20
    with pytest.raises(ValueError, match="only injects"):
        HandoffTransport(plan=FaultPlan("nan_grad"))
    with pytest.raises(ValueError, match="max_retries"):
        HandoffTransport(max_retries=-1)
    with pytest.raises(ValueError, match="wire"):
        HandoffTransport(wire="carrier_pigeon")


# ----------------------------------------------------------------------
# The socket wire (real localhost TCP, no engine)
# ----------------------------------------------------------------------

def test_tcp_wire_clean_roundtrip_bit_identical():
    """A clean tcp send really crosses a kernel socket and comes back
    byte-equal — same payload contract as the in-process wire."""
    mx = Metrics()
    t = HandoffTransport(metrics_obj=mx, wire="tcp")
    try:
        p = _payload()
        res = t.send(p, modeled_ms=0.5, rid=0)
        assert res.attempts == 1 and res.retries == 0
        np.testing.assert_array_equal(np.asarray(res.payload.k),
                                      np.asarray(p.k))
        np.testing.assert_array_equal(np.asarray(res.payload.v),
                                      np.asarray(p.v))
        snap = t.snapshot()
        assert snap["wire"] == "tcp" and snap["reset_total"] == 0
        assert snap["wire_drops"] == 0
    finally:
        t.close()


def test_tcp_wire_killed_mid_transfer_retries_bit_equal():
    """The wire is cut MID-STREAM (partial bytes really reach the
    receiver's socket, then the connection dies): the receiver
    discards the torn transfer, the sender reconnects and the retry
    delivers a bit-equal payload with the wasted time priced."""
    mx = Metrics()
    t = HandoffTransport(metrics_obj=mx, wire="tcp",
                         plan=FaultPlan("net_partition", step=0,
                                        duration=1))
    try:
        p = _payload()
        res = t.send(p, modeled_ms=0.5, rid=3, replica=1)
        assert res.attempts == 2 and res.retries == 1
        assert res.retry_ms > 0.5      # modeled wire time + backoff
        np.testing.assert_array_equal(np.asarray(res.payload.k),
                                      np.asarray(p.k))
        np.testing.assert_array_equal(np.asarray(res.payload.v),
                                      np.asarray(p.v))
        parts = [d for d in mx.decisions
                 if d["decision"] == "fabric.partition"]
        retries = [d for d in mx.decisions
                   if d["decision"] == "fabric.handoff_retry"]
        assert len(parts) == 1 and parts[0]["injected"] is True
        assert parts[0]["wire"] == "tcp"
        assert parts[0]["dropped_bytes"] > 0
        assert len(retries) == 1 and retries[0]["reason"] == "reset"
        # the receiver really saw (and refused) a partial stream
        assert t.snapshot()["wire_drops"] == 1
        # the next transfer is clean: the reconnect healed the wire
        res2 = t.send(_payload(8), modeled_ms=0.5, rid=4)
        assert res2.retries == 0
    finally:
        t.close()


def test_inproc_partition_plan_needs_no_socket():
    """net_partition on the in-process wire models the drop (no
    partial bytes exist to count) — the retry ladder is identical."""
    mx = Metrics()
    t = HandoffTransport(metrics_obj=mx,
                         plan=FaultPlan("net_partition", step=0,
                                        duration=1))
    res = t.send(_payload(), modeled_ms=0.5)
    assert res.retries == 1
    parts = [d for d in mx.decisions
             if d["decision"] == "fabric.partition"]
    assert len(parts) == 1 and parts[0]["wire"] == "inproc"
    assert parts[0]["dropped_bytes"] is None
    assert t.snapshot()["wire_drops"] == 0
    t.close()                      # idempotent on the socketless wire
    t.close()


# ----------------------------------------------------------------------
# Router fencing + engine evacuate/adopt (no fabric)
# ----------------------------------------------------------------------

def test_router_mark_failed_fences_and_last_death_raises():
    depths = {0: 5, 1: 1, 2: 3}
    router = ReplicaRouter(
        [lambda i=i: {"queue_depth": depths[i], "active_requests": 0}
         for i in range(3)], metrics_obj=Metrics(), affinity=False)
    assert router.route(100) == 1          # JSQ picks the shallowest
    router.mark_failed(1)
    assert router.failed() == (1,)
    for rid in range(101, 110):
        assert router.route(rid) != 1      # the corpse never serves
    router.mark_failed(2)
    assert all(router.route(rid) == 0 for rid in range(110, 115))
    router.mark_failed(0)
    with pytest.raises(RuntimeError, match="every replica has failed"):
        router.route(200)
    assert router.snapshot()["failed"] == [0, 1, 2]


def test_engine_evacuate_returns_all_and_adopt_front(params, trace):
    reqs, _ = trace
    eng = ServingEngine(params, CFG, SERVE, metrics_obj=Metrics())
    for r in reqs[:4]:
        eng.submit(r)
    for _ in range(2):          # some admitted, some still queued
        eng.step()
    inflight, queued = eng.evacuate()
    assert len(inflight) + len(queued) == 4 - len(eng.outputs)
    assert not eng.pending()    # nothing left behind on the corpse
    # in-flight victims carry their delivered tokens in the resumed
    # prompt (the bit-equal migration invariant)
    for entry in inflight:
        assert len(entry.req.prompt) >= len(entry.orig.prompt)
    adopter = ServingEngine(params, CFG, SERVE, metrics_obj=Metrics())
    tail = reqs[4]
    adopter.submit(tail)
    for entry in inflight:
        adopter.adopt(entry, front=True)
    # front adoption queues ahead of the local arrival and admits
    # immediately (arrival_step clamped to the adopter's clock);
    # each front insert prepends, so the head is the LAST adoptee
    head = adopter.queue[0]
    assert head.orig.rid == inflight[-1].orig.rid
    assert head.arrival_step <= adopter.step_idx
    assert adopter.stats["adopted"] == len(inflight)
    eng.close()
    adopter.close()


# ----------------------------------------------------------------------
# Fast per-fault smokes (mocked fabric, virtual clock)
# ----------------------------------------------------------------------

def test_fabric_crash_migration_bit_equal(params, trace, baseline,
                                          mock2):
    reqs, arrivals = trace
    mx = Metrics()
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock(),
                        fault_plan=FaultPlan("replica_crash", step=3,
                                             expert=0))
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    errs = door.validate()
    door.close()
    fab.close()
    _assert_bit_equal(out, baseline)
    assert errs == []
    crash = [d for d in mx.decisions
             if d["decision"] == "fabric.replica_crash"]
    mig = [d for d in mx.decisions if d["decision"] == "fabric.migrate"]
    assert len(crash) == 1 and crash[0]["replica"] == 0
    assert mig and all(d["from_replica"] == 0 for d in mig)
    assert fab.migrated == len(mig)
    assert fab.router.failed() == (0,)


def test_fabric_transport_corrupt_retries_and_bit_equal(params, trace,
                                                        baseline,
                                                        mock2):
    reqs, arrivals = trace
    mx = Metrics()
    t = HandoffTransport(metrics_obj=mx,
                         plan=FaultPlan("handoff_corrupt", step=1,
                                        duration=2))
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock(), transport=t)
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    errs = door.validate()
    door.close()
    fab.close()
    _assert_bit_equal(out, baseline)
    assert errs == []
    assert t.retries_total == 2      # one retry per faulted transfer
    drift = [d for d in mx.decisions
             if d["decision"] == "fabric.handoff_drift"]
    perturbed = [d for d in drift if d["retry_ms"] > 0]
    assert len(perturbed) == 2       # retry cost priced into the clock
    assert fab.handoff.snapshot()["transport"]["corrupt_total"] > 0


def test_frontdoor_brownout_sheds_and_recovers(params, mock2):
    flood, _ = build_requests(10, vocab=CFG.vocab_size, prompt_len=8,
                              max_new=6, seed=1, arrival_every=0)
    arrivals = [0, 0, 0, 0, 2, 2, 3, 3, 4, 5]
    mx = Metrics()
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock())
    door = FrontDoor(fab, brownout=BrownoutConfig(
        queue_high=2.0, queue_low=0.5, debounce_steps=1,
        cooldown_steps=2, episode_budget=2))
    out = door.run(flood, arrivals)
    errs = door.validate()
    snap = door.brownout_snapshot()
    door.close()
    fab.close()
    assert errs == []
    shed = [d for d in mx.decisions
            if d["decision"] == "frontdoor.shed"]
    trans = [d["state"] for d in mx.decisions
             if d["decision"] == "frontdoor.brownout"]
    assert snap["shed"] == len(shed) >= 1
    assert "enter" in trans and "exit" in trans
    # conservation: every offered request either completed or was shed
    assert len(out) + len(door.shed_rids) == len(flood)
    # admitted requests were never touched by the brownout
    assert all(rid not in out for rid in door.shed_rids)


def test_frontdoor_brownout_degrade_caps_tokens(params, mock2):
    flood, _ = build_requests(8, vocab=CFG.vocab_size, prompt_len=8,
                              max_new=6, seed=2, arrival_every=0)
    arrivals = [0, 0, 0, 0, 2, 2, 3, 4]
    mx = Metrics()
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock())
    door = FrontDoor(fab, brownout=BrownoutConfig(
        queue_high=2.0, queue_low=0.5, mode="degrade",
        degrade_max_new=2, debounce_steps=1, cooldown_steps=2))
    out = door.run(flood, arrivals)
    door.close()
    fab.close()
    degraded = [d for d in mx.decisions
                if d["decision"] == "frontdoor.shed"
                and d["mode"] == "degrade"]
    assert degraded and door.degraded_rids
    assert all(d["max_new_tokens"] == 2 for d in degraded)
    # degraded requests complete (short), nothing is dropped; outputs
    # echo the 8-token prompt, so the cap shows as prompt + 2
    assert len(out) == len(flood)
    for d in degraded:
        assert len(out[d["rid"]]) <= 8 + 2


def test_frontdoor_cluster_failover_bit_equal(params, trace, baseline,
                                              mock2):
    reqs, arrivals = trace
    mx = Metrics()
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock())
    cl = FrontDoorCluster(fab, n_doors=2, n_shards=8, metrics_obj=mx)
    out = cl.run(reqs, arrivals, fail_at=2, fail_peer=0)
    errs = cl.validate()
    snap = cl.snapshot()
    doc = cl.fleet_trace_document()
    cl.close()
    fab.close()
    _assert_bit_equal(out, baseline)
    assert errs == []                # zero orphan spans post-failover
    assert doc["traceEvents"]
    fo = [d for d in mx.decisions
          if d["decision"] == "frontdoor.failover"]
    assert fo and all(d["from_peer"] == 0 and d["to_peer"] != 0
                      for d in fo)
    assert all(d["epoch"] >= 1 for d in fo)
    assert snap["max_epoch"] >= 1 and snap["dead"] == [0]
    # every lease ended up owned by a survivor
    assert all(lease["owner"] != 0 for lease in cl.leases.values())


def test_fabric_replica_stall_heartbeat_migration_bit_equal(
        params, trace, baseline, mock2):
    """A replica hangs MID-STEP: its probe still answers, so only the
    sub-step heartbeat deadline catches it — then the same
    fence+evacuate+adopt migration as a probed crash, token-bit-equal."""
    from flashmoe_tpu.fabric import HeartbeatConfig

    reqs, arrivals = trace
    mx = Metrics()
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock(),
                        heartbeat=HeartbeatConfig(misses_to_stall=2),
                        fault_plan=FaultPlan("replica_stall", step=3,
                                             expert=0))
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    errs = door.validate()
    door.close()
    fab.close()
    _assert_bit_equal(out, baseline)
    assert errs == []
    stalls = [d for d in mx.decisions
              if d["decision"] == "fabric.heartbeat_stall"]
    misses = [d for d in mx.decisions
              if d["decision"] == "fabric.heartbeat_miss"]
    crash = [d for d in mx.decisions
             if d["decision"] == "fabric.replica_crash"]
    assert len(stalls) == 1 and stalls[0]["replica"] == 0
    assert stalls[0]["detect_ms"] > 0
    # detection is LATE by design: the hysteresis window, not the
    # hang step (the probe can never see a stall)
    assert stalls[0]["step"] > 3
    assert len(misses) == 2        # misses_to_stall consecutive
    assert len(crash) == 1 and fab.router.failed() == (0,)
    assert 0 in fab._stalled


def test_fabric_heartbeat_off_is_default_and_invisible(params, trace,
                                                       baseline, mock2):
    """heartbeat=None (the default) installs NO engine callback and
    no store file — the probe-only path byte-identical to PR 18."""
    reqs, arrivals = trace
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=Metrics(),
                        vclock=VirtualClock())
    assert fab.hb_watchdog is None
    assert all(e._heartbeat is None for e in fab.engines)
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    door.close()
    fab.close()
    _assert_bit_equal(out, baseline)


def test_fabric_heartbeat_armed_clean_run_bit_equal(params, trace,
                                                    baseline, mock2):
    """Heartbeats on with NO fault: zero misses, zero stalls, outputs
    bit-equal — the watchdog never false-positives on a healthy
    fleet."""
    from flashmoe_tpu.fabric import HeartbeatConfig

    reqs, arrivals = trace
    mx = Metrics()
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                        vclock=VirtualClock(),
                        heartbeat=HeartbeatConfig())
    store_path = fab._own_store_path
    assert store_path and os.path.exists(store_path)
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    door.close()
    fab.close()
    _assert_bit_equal(out, baseline)
    assert not [d for d in mx.decisions
                if d["decision"] in ("fabric.heartbeat_miss",
                                     "fabric.heartbeat_stall")]
    assert not os.path.exists(store_path)   # close() reaped the store


def test_frontdoor_cluster_store_parity_with_in_memory(params, trace,
                                                       baseline, mock2,
                                                       tmp_path):
    """The externally-stored lease table is a drop-in for the
    in-memory one: same failover decisions (shard/epoch/peers), same
    tokens, plus fencing on the store."""
    from flashmoe_tpu.fabric import LeaseStore, StaleLeaseError

    reqs, arrivals = trace

    def run_cluster(store):
        mx = Metrics()
        fab = ServingFabric(params, CFG, SERVE, metrics_obj=mx,
                            vclock=VirtualClock())
        cl = FrontDoorCluster(fab, n_doors=2, n_shards=8,
                              metrics_obj=mx, store=store)
        out = cl.run(reqs, arrivals, fail_at=2, fail_peer=0)
        snap = cl.snapshot()
        cl.close()
        fab.close()
        fo = [{k: d[k] for k in ("shard", "from_peer", "to_peer",
                                 "epoch")}
              for d in mx.decisions
              if d["decision"] == "frontdoor.failover"]
        return out, fo, snap

    store = LeaseStore(str(tmp_path / "leases.bin"),
                       metrics_obj=Metrics())
    out_mem, fo_mem, _ = run_cluster(None)
    out_ext, fo_ext, snap = run_cluster(store)
    _assert_bit_equal(out_mem, baseline)
    _assert_bit_equal(out_ext, baseline)
    assert fo_ext == fo_mem          # identical failover ledger
    assert snap["external_store"]
    # the store remembers across instances, and fences stale epochs
    reopened = LeaseStore(str(tmp_path / "leases.bin"),
                          metrics_obj=Metrics())
    moved = sorted(d["shard"] for d in fo_ext)
    assert moved and all(reopened.leases()[s].owner != 0
                         and reopened.leases()[s].epoch >= 1
                         for s in moved)
    shard = moved[0]
    with pytest.raises(StaleLeaseError):
        reopened.write_lease(shard, 0,
                             reopened.leases()[shard].epoch)


def test_frontdoor_cluster_validates_and_fences(params, mock2):
    fab = ServingFabric(params, CFG, SERVE, metrics_obj=Metrics(),
                        vclock=VirtualClock())
    cl = FrontDoorCluster(fab, n_doors=2, n_shards=8,
                          metrics_obj=Metrics())
    with pytest.raises(ValueError, match="door"):
        FrontDoorCluster(fab, n_doors=0)
    cl.fail_door(0)
    with pytest.raises(RuntimeError, match="last live"):
        cl.fail_door(1)
    cl.close()
    fab.close()


# ----------------------------------------------------------------------
# Registry / matrix bookkeeping
# ----------------------------------------------------------------------

def test_serving_faults_registered_with_tiers():
    for fault in SERVING_FAULTS:
        assert fault in FAULTS
        assert EXPECTED_TIER[fault].startswith("fabric:")
    for name in ("fabric.handoff_corrupt", "fabric.handoff_retry",
                 "fabric.migrate", "fabric.replica_crash",
                 "fabric.partition", "fabric.heartbeat_miss",
                 "fabric.heartbeat_stall", "frontdoor.brownout",
                 "frontdoor.failover", "frontdoor.fence",
                 "frontdoor.lease_repair", "frontdoor.shed"):
        assert name in DECISION_NAMES


def test_brownout_config_validates():
    with pytest.raises(ValueError):
        BrownoutConfig(queue_high=2.0, queue_low=3.0)
    with pytest.raises(ValueError):
        BrownoutConfig(mode="panic")
    with pytest.raises(ValueError):
        BrownoutConfig(degrade_max_new=0)
    with pytest.raises(ValueError):
        BrownoutConfig(episode_budget=0)


def test_reference_shed_frac_matches_committed_sentry_row():
    import json

    from flashmoe_tpu.telemetry_plane.regression import (
        _reference_shed_frac,
    )

    frac = _reference_shed_frac(BrownoutConfig())
    assert 0.0 < frac < 1.0
    hist = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "obs", "history.jsonl")
    with open(hist) as f:
        entry = json.loads(f.readline())
    row = entry["metrics"]["fabric_shed_frac[brownout,reference]"]
    assert row["value"] == pytest.approx(round(frac, 4))
    assert row["unit"] == "frac"


def test_fabric_fault_sweep_record_contract(monkeypatch):
    """The bench sweep's record shape, with the drills faked out —
    the real drills run under the slow mark below."""
    from flashmoe_tpu.chaos import drill as drill_mod
    from flashmoe_tpu.serving import loadgen

    def fake_drill(fault, *, seed=0, **kw):
        return drill_mod.DrillResult(
            fault=fault, expected_tier=EXPECTED_TIER[fault],
            recovered=(fault != "handoff_timeout"), reason="boom",
            final_step=6, steps_rerun=0, wall_s=0.123,
            evidence={"completed": 6, "bit_equal_to_baseline": True,
                      "migrations": 2, "retries": 1, "corrupt": 1,
                      "failovers": 0, "trace_errors": []},
            decisions=[])

    monkeypatch.setattr(drill_mod, "run_drill", fake_drill)
    monkeypatch.setattr(loadgen, "_brownout_shed_record",
                        lambda *, seed=0: {"metric":
                                           "fabric_shed[brownout]",
                                           "value": 0.4,
                                           "unit": "frac"})
    recs = loadgen.fabric_fault_sweep(seed=0)
    assert [r["metric"] for r in recs] == [
        "fabric_fault[replica_crash]", "fabric_fault[handoff_corrupt]",
        "fabric_fault[handoff_timeout]",
        "fabric_fault[frontdoor_loss]", "fabric_fault[net_partition]",
        "fabric_fault[lease_split_brain]",
        "fabric_fault[replica_stall]",
        "fabric_fault[lease_torn_write]", "fabric_shed[brownout]"]
    crash = recs[0]
    assert crash["unit"] == "ms" and crash["value"] == 123.0
    assert crash["migrated"] == 2 and crash["retries"] == 1
    assert crash["bit_equal"] is True and "error" not in crash
    # an unrecovered drill carries error so the sentry skips it
    assert recs[2]["error"] == "boom"
    with pytest.raises(ValueError, match="not serving faults"):
        loadgen.fabric_fault_sweep(["nan_grad"])


# ----------------------------------------------------------------------
# The chaos-matrix drills (slow lane)
# ----------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("fault", SERVING_FAULTS)
def test_serving_fault_drill_recovers(fault):
    from flashmoe_tpu.chaos.drill import run_drill

    r = run_drill(fault)
    assert r.recovered, f"{fault}: {r.reason}"
    ev = r.evidence
    assert ev["bit_equal_to_baseline"] is True
    assert ev["trace_errors"] == []
    assert ev["fleet_trace_events"] > 0
    if fault == "replica_crash":
        assert ev["crashes"] == 1 and ev["migrations"] >= 1
    elif fault in ("handoff_corrupt", "handoff_timeout"):
        assert ev["retries"] == 2 and ev["retried_drift"] == 2
    elif fault == "frontdoor_loss":
        assert ev["failovers"] >= 1
    elif fault == "net_partition":
        # real socket cuts: partial bytes crossed, retried as resets
        assert ev["partitions"] == 2 and ev["retries"] == 2
        assert ev["retried_drift"] == 2
    elif fault == "lease_split_brain":
        assert ev["zombie_attempts"] >= 1
        assert ev["zombie_refused"] == ev["zombie_attempts"]
        assert ev["fences"] == ev["zombie_refused"]
    elif fault == "replica_stall":
        assert ev["stalls"] == 1 and ev["heartbeat_misses"] >= 2
        assert ev["crashes"] == 1 and ev["migrations"] >= 1
    elif fault == "lease_torn_write":
        assert ev["lease_repairs"] >= 1 and ev["torn_bytes"] > 0
        assert ev["restored_epoch"] == 1 and ev["failovers"] >= 1


# ----------------------------------------------------------------------
# Speculative decoding under faults (ISSUE 20)
# ----------------------------------------------------------------------

def _spec_serve(k: int = 3) -> ServeConfig:
    from flashmoe_tpu.serving.speculate import SpecConfig

    return dataclasses.replace(SERVE, speculate=SpecConfig(draft_tokens=k))


@pytest.fixture(scope="module")
def spec_trace():
    """Repetitive prompts (tiled bigram motifs): the n-gram drafter has
    suffix matches to propose from, so the fault drills exercise real
    acceptance instead of the empty-draft fallthrough."""
    return build_requests(6, vocab=CFG.vocab_size, prompt_len=8,
                          max_new=6, seed=3, arrival_every=1,
                          repetitive=True)


@pytest.fixture(scope="module")
def spec_baseline(params, spec_trace):
    """Gold standard for the speculative drills: the same trace through
    one uninterrupted NON-speculative engine — exact rejection sampling
    must hold through crashes and morphs, not just clean runs."""
    reqs, arrivals = spec_trace
    eng = ServingEngine(params, CFG, SERVE, metrics_obj=Metrics())
    out = eng.run(reqs, arrivals)
    eng.close()
    return out


@pytest.mark.slow
def test_fabric_crash_migration_spec_bit_equal(params, spec_trace,
                                               spec_baseline, mock2):
    """A replica dies mid-stream with speculation armed: the migrated
    requests re-prefill on the adopter, the DraftState rebuilds from
    ``prompt + emitted``, and every stream stays token-bit-equal to the
    non-speculative single-engine oracle."""
    reqs, arrivals = spec_trace
    mx = Metrics()
    fab = ServingFabric(params, CFG, _spec_serve(), metrics_obj=mx,
                        vclock=VirtualClock(),
                        fault_plan=FaultPlan("replica_crash", step=3,
                                             expert=0))
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    errs = door.validate()
    summ = fab.summary()
    door.close()
    fab.close()
    _assert_bit_equal(out, spec_baseline)
    assert errs == []
    crash = [d for d in mx.decisions
             if d["decision"] == "fabric.replica_crash"]
    assert len(crash) == 1 and crash[0]["replica"] == 0
    assert [d for d in mx.decisions
            if d["decision"] == "fabric.migrate"]
    # not vacuous: drafts flowed (and some were accepted) fleet-wide
    assert summ["spec"]["spec_drafted"] > 0
    assert summ["spec"]["spec_accepted"] > 0
    assert summ["spec"]["spec_on"] == [True, True]


@pytest.mark.slow
def test_fabric_spec_morph_drill_zero_lost_tokens(params, spec_trace,
                                                  spec_baseline, mock2):
    """The controller drill the ISSUE names: a fleet running with an
    unreachable acceptance floor morphs speculation OFF on every
    replica at once (a per-replica split would fork measurement
    identity), loses zero tokens, and stays bit-equal — exact
    rejection sampling makes the morph free."""
    from flashmoe_tpu.runtime.controller import (
        ControllerConfig, RuntimeController,
    )

    reqs, arrivals = spec_trace
    mx = Metrics()
    cc = ControllerConfig(enable_spec_morph=True, spec_accept_floor=0.99,
                          debounce_steps=1, cooldown_steps=2)
    ctl = RuntimeController(CFG, cc, metrics=mx)
    fab = ServingFabric(params, CFG, _spec_serve(), metrics_obj=mx,
                        vclock=VirtualClock(), controller=ctl)
    door = FrontDoor(fab)
    out = door.run(reqs, arrivals)
    errs = door.validate()
    summ = fab.summary()
    door.close()
    fab.close()
    _assert_bit_equal(out, spec_baseline)        # zero lost tokens
    assert errs == []
    assert ctl.spec_morphs_used == 1
    assert summ["spec"]["spec_on"] == [False, False]
    morphs = [d for d in mx.decisions
              if d["decision"] == "controller.spec_morph"]
    assert len(morphs) == 1
    assert morphs[0]["trigger"] == "accept_low"
