"""Measured-latency fabric (PR 17): the front door's trace/session
authority, the deterministic virtual clock, and per-request SLO
attribution.

The headline drill is the ISSUE acceptance: the mocked 2-pool x
2-replica fabric stepping on a :class:`VirtualClock` behind ONE
:class:`FrontDoor` — every request's spans land on one fleet-wide
track (``validate_trace``-gated Perfetto document with cross-pool flow
events), TTFT/TPOT are measured UNDER the modeled DCN handoff delay,
each transfer's measured hidden/exposed split reconciles with the
priced overlap verdict (``fabric.handoff_drift``), and the
critical-path attribution sums to each request's span total within 1%.
Clocks and names never touch math: the drill is token-bit-equal to the
plain PR 15 fabric on the same trace.
"""

import json
import os

import jax
import numpy as np
import pytest

from flashmoe_tpu.chaos import FaultPlan
from flashmoe_tpu.fabric import FrontDoor, ServingFabric, VirtualClock
from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
from flashmoe_tpu.fabric.vclock import DCN_FAULTS
from flashmoe_tpu.models.transformer import init_params
from flashmoe_tpu.profiler.export import validate_trace
from flashmoe_tpu.serving.engine import Request, ServeConfig
from flashmoe_tpu.serving.loadgen import (
    merge_traces, split_requests, tiny_config,
)
from flashmoe_tpu.telemetry_plane.attribution import (
    COMPONENTS, attribute_track,
)
from flashmoe_tpu.utils.telemetry import Metrics

CFG = tiny_config()


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def prompts():
    return jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0,
                              CFG.vocab_size)


def _requests(prompts, n, max_new=6, **kw):
    return [Request(rid=i, prompt=tuple(int(t) for t in prompts[i]),
                    max_new_tokens=max_new, **kw) for i in range(n)]


def _serve(**kw):
    base = dict(max_batch=4, page_size=8, num_pages=8,
                max_pages_per_slot=4, ctx_bucket_pages=1,
                prompt_bucket=8)
    base.update(kw)
    return ServeConfig(**base)


# ----------------------------------------------------------------------
# VirtualClock unit semantics
# ----------------------------------------------------------------------

def test_vclock_lane_and_hidden_exposed_math():
    """Per-lane accounting: a transfer hides under the remaining
    decode-tick budget and exposes the rest; complete_step never
    double-bills handoff time; lanes are independent."""
    vc = VirtualClock(tick_ms=2.0, lanes=2)
    assert vc() == 0.0 and vc.now_ms() == 0.0

    vc.use_lane(0)
    a = vc.on_handoff(1.5, rid=0, replica=0)
    assert a["hidden_ms"] == 1.5 and a["exposed_ms"] == 0.0
    # second transfer in the SAME step: only 0.5 ms of budget left
    b = vc.on_handoff(1.5, rid=1, replica=0)
    assert b["hidden_ms"] == 0.5 and b["exposed_ms"] == 1.0
    # step total = max(tick, handoffs) = 3.0 ms, not tick + handoffs
    idle = vc.complete_step()
    assert idle == 0.0
    assert vc.now_ms() == pytest.approx(3.0)

    # lane 1 never moved; an idle step costs exactly one tick there
    vc.use_lane(1)
    assert vc.now_ms() == 0.0
    vc.complete_step()
    assert vc.now_ms() == pytest.approx(2.0)

    # rollups
    assert vc.measured_ms_total == pytest.approx(3.0)
    assert vc.hidden_ms_total == pytest.approx(2.0)
    assert vc.hidden_fraction() == pytest.approx(2.0 / 3.0)
    snap = vc.snapshot()
    assert snap["lanes"] == 2 and snap["transfers"] == 2
    assert snap["fault"] is None

    # ensure_lanes grows, use_lane auto-grows
    vc.use_lane(3)
    assert len(vc.snapshot()["lane_s"]) == 4


def test_vclock_chaos_window_and_determinism():
    """dcn_latency adds a constant inside the transfer-index window
    only; dcn_jitter is a seeded crc32 draw — two clocks with the same
    plan replay bit-identically, a different seed perturbs
    differently; non-DCN faults are rejected at construction."""
    plan = FaultPlan("dcn_latency", step=1, duration=2, latency_ms=5.0)
    vc = VirtualClock(tick_ms=0.0, plan=plan)
    accts = [vc.on_handoff(1.0) for _ in range(4)]
    assert [a["chaos_ms"] for a in accts] == [0.0, 5.0, 5.0, 0.0]
    assert [a["measured_ms"] for a in accts] == [1.0, 6.0, 6.0, 1.0]

    jp = FaultPlan("dcn_jitter", step=0, duration=8, jitter_ms=3.0,
                   seed=7)
    v1 = VirtualClock(tick_ms=0.0, plan=jp)
    v2 = VirtualClock(tick_ms=0.0, plan=jp)
    c1 = [v1.on_handoff(1.0)["chaos_ms"] for _ in range(8)]
    c2 = [v2.on_handoff(1.0)["chaos_ms"] for _ in range(8)]
    assert c1 == c2                          # deterministic replay
    assert all(0.0 <= c <= 3.0 for c in c1)
    assert len(set(c1)) > 1                  # actually jitters
    v3 = VirtualClock(
        tick_ms=0.0,
        plan=FaultPlan("dcn_jitter", step=0, duration=8, jitter_ms=3.0,
                       seed=8))
    c3 = [v3.on_handoff(1.0)["chaos_ms"] for _ in range(8)]
    assert c3 != c1                          # seed matters

    with pytest.raises(ValueError, match="dcn_latency"):
        VirtualClock(plan=FaultPlan("slow_step"))
    assert set(DCN_FAULTS) == {"dcn_latency", "dcn_jitter"}


def test_attribute_track_sum_gate_and_clip():
    """The decomposition must cover the span: a synthetic track
    attributes exactly, the TTFT clip (until_ms) re-attributes the
    prefix, and a router spill reclassifies queue wait."""
    track = [
        {"name": "serve.queued", "ts_ms": 0.0, "dur_ms": 2.0,
         "rid": 0},
        {"name": "serve.step", "ts_ms": 2.0, "dur_ms": 3.0,
         "rid": 0},
        {"name": "serve.prefill", "ts_ms": 2.0, "dur_ms": 3.0,
         "rid": 0},
        {"name": "serve.handoff", "ts_ms": 3.0, "dur_ms": 1.0,
         "rid": 0},
        {"name": "serve.queued", "ts_ms": 5.0, "dur_ms": 1.0,
         "rid": 0, "resumed": True},
        {"name": "serve.step", "ts_ms": 6.0, "dur_ms": 4.0,
         "rid": 0},
    ]
    att = attribute_track(track)
    assert set(att["components"]) == set(COMPONENTS)
    assert att["sum_ok"] and att["rel_err"] <= 0.01
    assert att["span_ms"] == pytest.approx(10.0)
    assert att["components"]["queue_wait"] == pytest.approx(2.0)
    assert att["components"]["handoff_dcn"] == pytest.approx(1.0)
    assert att["components"]["prefill"] == pytest.approx(2.0)
    assert att["components"]["eviction_gap"] == pytest.approx(1.0)
    assert att["components"]["decode_steps"] == pytest.approx(4.0)
    assert att["dominant"] == "decode_steps"

    clipped = attribute_track(track, until_ms=5.0)   # the TTFT prefix
    assert clipped["sum_ok"]
    assert clipped["span_ms"] == pytest.approx(5.0)
    assert clipped["components"]["decode_steps"] == 0.0

    spill = attribute_track(track, spilled=True)
    assert spill["components"]["router_spill"] == pytest.approx(2.0)
    assert spill["components"]["queue_wait"] == 0.0
    assert spill["sum_ok"]


# ----------------------------------------------------------------------
# The 2-pool x 2-replica measured drill (ISSUE acceptance)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def drill(params, prompts, tmp_path_factory):
    """Run the PR 15 fabric and the measured (vclock + front door)
    fabric ONCE on the same trace; every acceptance gate below reads
    this dict."""
    old = os.environ.get(ENV_MOCK_FABRIC)
    os.environ[ENV_MOCK_FABRIC] = "2"
    try:
        serve = _serve()
        arrivals = [0, 0, 0, 0, 1, 1, 2, 3]

        # PR 15 path: no vclock, no front door
        mx0 = Metrics()
        fab0 = ServingFabric(params, CFG, serve, metrics_obj=mx0)
        out0 = fab0.run(_requests(prompts, 8, max_new=10), arrivals)
        s0 = fab0.summary()
        fab0.close()

        # measured path: virtual clock behind the front door
        mx = Metrics()
        vc = VirtualClock()
        fab = ServingFabric(params, CFG, serve, metrics_obj=mx,
                            vclock=vc)
        door = FrontDoor(fab)
        out = door.run(_requests(prompts, 8, max_new=10), arrivals)
        s = fab.summary()
        att = door.attribution()
        trace_errors = door.validate()
        doc = door.fleet_trace_document()
        shard_dir = tmp_path_factory.mktemp("fleet")
        n_spans = door.export_jsonl(
            str(shard_dir / "telemetry.prefill.jsonl"))
        mx.dump_decisions_jsonl(
            str(shard_dir / "telemetry.prefill.jsonl"))
        door.close()
        fab.close()
        return {
            "out0": out0, "s0": s0, "out": out, "s": s, "att": att,
            "vc": vc, "mx": mx, "doc": doc, "errors": trace_errors,
            "shard_dir": shard_dir, "n_spans": n_spans,
        }
    finally:
        if old is None:
            os.environ.pop(ENV_MOCK_FABRIC, None)
        else:
            os.environ[ENV_MOCK_FABRIC] = old


def test_drill_token_bit_equal_and_off_identity(drill):
    """The clock and the namespace own time and names, never math:
    same tokens with and without them — and the OFF path carries no
    measured keys (the PR 15 summary shape is untouched)."""
    assert len(drill["out"]) == 8
    for i in range(8):
        np.testing.assert_array_equal(np.asarray(drill["out"][i]),
                                      np.asarray(drill["out0"][i]))
    assert "handoff_ms_measured" not in drill["s0"]
    assert "handoff_hidden_frac" not in drill["s0"]
    # same routing story (the door delegates placement to the router)
    assert drill["s"]["routed"] == drill["s0"]["routed"]
    assert drill["s"]["placement"] == drill["s0"]["placement"]


def test_drill_measured_summary_and_drift_reconciles(drill):
    """Every transfer got a measured verdict and the unperturbed drill
    reconciles: measured hidden/exposed agrees with the priced overlap
    verdict per transfer, and the summary's hidden fraction is the
    clock's."""
    s, vc, mx = drill["s"], drill["vc"], drill["mx"]
    assert s["handoffs"] >= 1
    assert s["handoff_ms_measured"] > 0
    assert s["handoff_verdicts_total"] == s["handoffs"]
    drift = [d for d in mx.decisions
             if d["decision"] == "fabric.handoff_drift"]
    assert len(drift) == s["handoffs"]
    for d in drift:
        assert d["measured_dcn_ms"] == pytest.approx(
            d["modeled_dcn_ms"])          # no chaos armed
        assert d["chaos_ms"] == 0.0
        assert d["hidden_ms"] + d["exposed_ms"] == pytest.approx(
            d["measured_dcn_ms"], abs=1e-6)
        assert d["agree"] is not False    # measured == priced verdict
    assert s["handoff_verdicts_agree"] == len(
        [d for d in drift if d["agree"]])
    assert s["handoff_hidden_frac"] == pytest.approx(
        vc.hidden_fraction())
    # /vars mirrors the clock
    assert len(vc.transfers) == s["handoffs"]


def test_drill_attribution_sums_within_gate(drill):
    """Per-request critical-path attribution: every retired request
    decomposes into the six components and sums to its span total
    within the 1% gate; a dominant contributor is always named and
    rides the serve.attribution decision + /metrics sketches."""
    att, mx = drill["att"], drill["mx"]
    assert set(att) == set(range(8))
    for rid, a in att.items():
        assert a["sum_ok"], (rid, a)
        assert a["dominant"] in COMPONENTS
        assert a["components"]["handoff_dcn"] >= 0.0
    decs = [d for d in mx.decisions
            if d["decision"] == "serve.attribution"]
    assert len(decs) == 8
    assert all(d["sum_ok"] for d in decs)
    assert any(k.startswith("serve.attr.") for k in mx.sketches)
    # the front door owned every submit
    subs = [d for d in mx.decisions
            if d["decision"] == "frontdoor.submit"]
    assert len(subs) == 8
    assert subs[-1]["submitted"] == 8


def test_drill_fleet_trace_document_valid_with_flows(drill):
    """ONE Perfetto document for the whole fleet: validate_trace-clean,
    a process track per replica, and explicit 's'/'f' flow events
    linking the prefill-pool span to the decode-pool resume of each
    handed-off request."""
    assert drill["errors"] == []          # tracer contiguity gate
    doc = drill["doc"]
    assert validate_trace(doc) == []
    evs = doc["traceEvents"]
    procs = [e for e in evs if e.get("ph") == "M"
             and e.get("name") == "process_name"]
    assert len({e["pid"] for e in procs}) >= 2
    starts = [e for e in evs if e.get("ph") == "s"]
    finishes = [e for e in evs if e.get("ph") == "f"]
    assert starts and finishes
    # every flow id pairs up, and at least one crosses processes
    by_id = {}
    for e in starts + finishes:
        by_id.setdefault(e["id"], []).append(e)
    assert all(len(v) >= 2 for v in by_id.values())
    assert any(len({e["pid"] for e in v}) == 2 for v in by_id.values())


def test_drill_duplicate_rid_rejected(params, prompts, monkeypatch):
    """The namespace is owned at the door: a rid submits at most once."""
    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")
    fab = ServingFabric(params, CFG, _serve(num_pages=32),
                        metrics_obj=Metrics())
    door = FrontDoor(fab)
    try:
        reqs = _requests(prompts, 2, max_new=4)
        door.submit(reqs[0])
        with pytest.raises(ValueError, match="already submitted"):
            door.submit(reqs[0])
        door.submit(reqs[1], session="s0")
        assert door.sessions == {"s0": [1]}
        while fab.pending():
            fab.step()
    finally:
        door.close()
        fab.close()


def test_frontdoor_token_bit_equal_to_presplit(params, prompts,
                                               monkeypatch):
    """Satellite gate: the SAME merged pre-split trace driven through
    the plain fabric (the loadgen pre-split path) and through the
    front door yields token-bit-equal outputs — adopting the door
    changes ownership, not results."""
    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")
    reqs, arrivals = merge_traces(split_requests(
        4, replicas=2, vocab=CFG.vocab_size, prompt_len=8, max_new=5,
        seed=3, arrival_every=1))
    serve = _serve(num_pages=32)

    fab0 = ServingFabric(params, CFG, serve, metrics_obj=Metrics())
    out0 = fab0.run(reqs, arrivals)
    fab0.close()

    fab1 = ServingFabric(params, CFG, serve, metrics_obj=Metrics())
    door = FrontDoor(fab1)
    out1 = door.run(reqs, arrivals)
    door.close()
    fab1.close()

    assert sorted(out0) == sorted(out1)
    for rid in out0:
        np.testing.assert_array_equal(np.asarray(out0[rid]),
                                      np.asarray(out1[rid]))


# ----------------------------------------------------------------------
# Measured golden gate: fp8 flips the verdict on MEASURED numbers
# ----------------------------------------------------------------------

def test_measured_fp8_flips_golden_verdict():
    """Re-run the frozen golden fabric points through an actual
    VirtualClock (tick = the golden decode step, one handoff of the
    priced cost): the measured verdict (exposed == 0) must equal the
    priced one for every (config, gen, wire), and the fp8 page wire
    must flip at least one verdict ON MEASURED NUMBERS — the PR 15
    pricing property, now experienced."""
    from flashmoe_tpu.planner.golden import GOLDEN_PATH

    with open(GOLDEN_PATH) as f:
        fabric = json.load(f)["fabric"]
    flipped = 0
    for name, gens in fabric.items():
        for gen, point in gens.items():
            tick = point["decode_plan"]["total_ms"]
            measured = {}
            for tag, w in point["wires"].items():
                vc = VirtualClock(tick_ms=tick)
                acct = vc.on_handoff(w["handoff_ms"])
                vc.complete_step()
                overlapped_measured = acct["exposed_ms"] <= 1e-9
                assert overlapped_measured == w["overlapped"], (
                    name, gen, tag)
                # the step stretched by exactly the exposed remainder
                assert vc.now_ms() == pytest.approx(
                    max(tick, w["handoff_ms"]), abs=1e-6)
                measured[tag] = overlapped_measured
            if measured["e4m3"] and not measured["off"]:
                flipped += 1
    assert flipped >= 1, (
        "no golden point where the fp8 page wire flips the MEASURED "
        "handoff verdict — the virtual clock lost the pricing's teeth")


# ----------------------------------------------------------------------
# Chaos: the DCN faults drill through the measured plane
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_dcn_latency_drill_recovers():
    """One matrix row of the chaos drill (slow, like every drill
    test): the dcn_latency fault perturbs transfers, the drift
    decisions carry measured > modeled inside the window, attribution
    stays sum-gated, and the drill self-verifies."""
    from flashmoe_tpu.chaos.drill import run_drill

    res = run_drill("dcn_latency", seed=0)
    assert res.recovered, res.evidence
    assert res.evidence["perturbed_transfers"] >= 1
    assert res.evidence["handoffs"] == res.evidence["drift_decisions"]
    assert all(res.evidence["attribution_sum_ok"])


# ----------------------------------------------------------------------
# observe: fleet-shard dedupe + --attribution
# ----------------------------------------------------------------------

@pytest.fixture()
def shards(drill, tmp_path):
    """Two pool shards that both witnessed the drill (the decode shard
    is a byte-copy of the prefill one — the double-witness worst
    case)."""
    src = drill["shard_dir"] / "telemetry.prefill.jsonl"
    dst = tmp_path / "telemetry.decode.jsonl"
    dst.write_text(src.read_text())
    return [str(src), str(dst)]


def test_observe_trace_dedupes_fleet_shards(shards):
    from flashmoe_tpu.observe import (
        load_jsonl, render_trace_text, trace_report,
    )

    recs = load_jsonl(shards)
    rep = trace_report(recs, 1)
    assert rep["found"]
    assert rep["spans_deduped"] == len(rep["spans"])   # exact doubles
    names = {s["name"] for s in rep["spans"]}
    assert "serve.prefill" in names
    assert "shard-duplicate span(s) collapsed" in render_trace_text(rep)
    # a single shard has nothing to collapse
    one = trace_report(load_jsonl(shards[:1]), 1)
    assert one["spans_deduped"] == 0
    assert len(one["spans"]) == len(rep["spans"])


def test_observe_merge_dedupes_double_witnessed_handoffs(shards,
                                                        drill):
    from flashmoe_tpu.observe import merge_report, render_merge_text

    rep = merge_report(shards)
    assert set(rep["hosts"]) == {"prefill", "decode"}
    assert rep["handoffs_deduped"] == drill["s"]["handoffs"]
    assert "double-witnessed handoff(s) collapsed" in \
        render_merge_text(rep)


def test_observe_attribution_report_matches_door(shards, drill):
    """The offline report over exported (double-witnessed) shards
    reproduces the live door's attribution: same requests, same
    dominants, all sum-gated."""
    from flashmoe_tpu.observe import load_jsonl, render_attribution_text
    from flashmoe_tpu.telemetry_plane.attribution import (
        attribution_report,
    )

    rep = attribution_report(load_jsonl(shards))
    assert rep["requests"] == 8 and not rep["sum_violations"]
    for rid, a in rep["per_request"].items():
        live = drill["att"][rid]
        assert a["dominant"] == live["dominant"]
        assert a["span_ms"] == pytest.approx(live["span_ms"])
    text = render_attribution_text(rep)
    assert "latency attribution: 8 retired request(s)" in text
    assert "dominant" in text
