"""Fused in-kernel all-to-all MoE (remote-DMA interpret emulation)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.parallel.fused import fused_ep_moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _setup(cfg, seed=0):
    pk, xk = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(pk, cfg)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), jnp.float32)
    return params, x


@pytest.mark.parametrize("ep", [2, 4])
def test_fused_matches_oracle(ep, devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=ep, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:ep])
    out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_fused_matches_ep_layer_with_drops(devices):
    """Same drops/renormalization as the collective EP path."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=512,
                    capacity_factor=1.0, drop_tokens=True, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    got = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want.out), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_array_equal(
        np.asarray(got.expert_counts), np.asarray(want.expert_counts)
    )


def test_fused_race_detector_clean(devices):
    """The interpreter's vector-clock race detector over the fused kernel's
    RDMA/semaphore protocol — the sanitizer the reference never had."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                             detect_races=True)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_fused_skewed_tile_skipping(devices):
    """All tokens to one remote expert: most slabs/tiles are empty and
    must be skipped on both send and wait sides without deadlock, while
    the loaded expert's tiles all arrive."""
    cfg = MoEConfig(num_experts=8, expert_top_k=1, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=4, **F32)
    params, x = _setup(cfg)
    params["gate_w"] = jnp.zeros_like(params["gate_w"]).at[:, 5].set(1.0)
    x = jnp.abs(x) + 0.1
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                             detect_races=True)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    assert int(out.expert_counts[5]) == cfg.tokens


@pytest.mark.parametrize("variant", ["plain", "gated", "drops"])
@pytest.mark.slow
def test_fused_gradients_match_collective_path(variant, devices):
    """The fused RDMA layer's custom VJP (XLA re-exchange + Pallas GEMM
    backward) must produce the same gradients as autodiff through the
    collective EP path — including the gated (SwiGLU) branch (g recompute,
    d_gate, d_wg) and the count-skewed drop path (zero cotangents on
    skipped tiles vs the full-slab backward)."""
    extra = {}
    if variant == "gated":
        extra = dict(gated_ffn=True, hidden_act="silu")
    if variant == "drops":
        extra = dict(capacity_factor=1.0, drop_tokens=True)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=extra.pop("drop_tokens", False), ep=2,
                    **extra, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])

    def loss_fused(p, xx):
        o = fused_ep_moe_layer(p, xx, cfg, mesh, interpret=True)
        return (o.out.astype(jnp.float32) ** 2).sum()

    def loss_coll(p, xx):
        o = ep_moe_layer(p, xx, cfg, mesh, use_pallas=False)
        return (o.out.astype(jnp.float32) ** 2).sum()

    gf = jax.grad(loss_fused, argnums=(0, 1))(params, x)
    gc = jax.grad(loss_coll, argnums=(0, 1))(params, x)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gc[1]),
                               rtol=5e-3, atol=5e-3)
    for k in gc[0]:
        np.testing.assert_allclose(
            np.asarray(gf[0][k]), np.asarray(gc[0][k]),
            rtol=5e-3, atol=5e-3, err_msg=k,
        )


@pytest.mark.slow
def test_fused_non_tile_multiple_capacity(devices):
    """capacity_factor=1.25 at S=512/ep=2 gives cap=80 per (rank,
    expert) — padded to 96, not a multiple of 256.  The kernel must
    degrade its row tile (cm=32) / pad rather than raise (advisor
    finding, round 1), and still match the collective EP path."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=512,
                    capacity_factor=1.25, drop_tokens=True, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    got = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want.out), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("mode", ["1", "0"], ids=["in_kernel", "xla"])
@pytest.mark.slow
def test_fused_combine_modes_match_oracle(mode, monkeypatch, devices):
    """FLASHMOE_FUSED_COMBINE forces each combine implementation; both
    must match the dense oracle (and hence each other) — incl. drops,
    where empty slots hold unwritten slab memory the in-kernel combine
    must never read."""
    monkeypatch.setenv("FLASHMOE_FUSED_COMBINE", mode)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    got = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                             detect_races=(mode == "1"))
    want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want.out), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_fused_gated_with_shared_experts(devices):
    """SwiGLU experts stream through the kernel; shared experts add in."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=4, gated_ffn=True,
                    hidden_act="silu", num_shared_experts=1, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_fuse_combine_gate_is_opt_in(monkeypatch):
    """The in-kernel combine is opt-in until a hardware stage_bench row
    justifies a default (advisor r3 #1/#2): env unset -> XLA combine;
    env=1 -> enabled only within the SMEM/VMEM budget, with a warning
    (not a Mosaic compile failure) when the combine maps are too large.
    Since the round-5 sorted-return restructure it also requires a
    multi-rank ep world — at world 1 there is no communication to
    overlap and the per-row return copies are pure overhead."""
    from flashmoe_tpu.parallel.fused import _fuse_combine_enabled

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=2, **F32)
    monkeypatch.delenv("FLASHMOE_FUSED_COMBINE", raising=False)
    assert not _fuse_combine_enabled(cfg, 256, 128, 256, 64)

    monkeypatch.setenv("FLASHMOE_FUSED_COMBINE", "1")
    assert _fuse_combine_enabled(cfg, 256, 128, 256, 64)

    # single-rank world: nothing to overlap -> XLA combine even when asked
    assert not _fuse_combine_enabled(cfg, 256, 128, 256, 64, d_world=1)
    assert not _fuse_combine_enabled(cfg.replace(ep=1), 256, 128, 256, 64)

    # 4096 experts x 4096-slot capacity: the sorted-row map alone is
    # 64 MiB of SMEM — must fall back (with a warning), never Mosaic-fail
    big = cfg.replace(num_experts=4096)
    with pytest.warns(UserWarning, match="SMEM/VMEM budget"):
        assert not _fuse_combine_enabled(big, 256, 128, 256, 4096)

    monkeypatch.setenv("FLASHMOE_FUSED_COMBINE", "0")
    assert not _fuse_combine_enabled(cfg, 256, 128, 256, 64)


@pytest.mark.parametrize("resident", [True, False], ids=["resident",
                                                         "streaming"])
def test_fused_weights_resident_matches_oracle(resident, monkeypatch,
                                               tmp_path, devices):
    """The weights-resident two-pass schedule (weights stream HBM->VMEM
    once per expert, x re-streams per chunk) must be numerically
    identical to the per-row-tile streaming schedule — forced each way
    through the tuning table's ``weights_resident`` knob on a
    multi-row-tile shape (cap 128 / cm tuned to 32 -> 4 row tiles)."""
    import json

    from flashmoe_tpu import tuning

    table = {"generation": "test", "entries": [{
        "kernel": "fused_ep", "match": {"h": 128},
        "set": {"cm": 32, "weights_resident": resident},
    }]}
    p = tmp_path / "tuning.json"
    p.write_text(json.dumps(table))
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(p))
    tuning._load.cache_clear()
    try:
        cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                        intermediate_size=256, sequence_len=512,
                        drop_tokens=False, ep=2, **F32)
        params, x = _setup(cfg)
        mesh = make_mesh(cfg, dp=1, devices=devices[:2])
        out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
        )
    finally:
        tuning._load.cache_clear()


def test_fused_batched_schedule_matches_per_source(monkeypatch, devices):
    """The arrival-batched schedule (default at ep >= 3: own slab at
    step 0, remote slabs expert-major at the final step with weights
    streamed once — the fix for the d x weight re-streaming the round-5
    cost model exposed) must be numerically identical to the per-source
    schedule and the oracle, drops included."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=512,
                    capacity_factor=1.0, drop_tokens=True, ep=4, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    batched = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                                 detect_races=True)
    monkeypatch.setenv("FLASHMOE_FUSED_BATCHED", "0")
    per_src = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED")
    np.testing.assert_allclose(np.asarray(batched.out),
                               np.asarray(per_src.out),
                               rtol=1e-5, atol=1e-5)
    want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
    np.testing.assert_allclose(np.asarray(batched.out),
                               np.asarray(want.out),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_fused_batched_with_in_kernel_combine(monkeypatch, devices):
    """The two round-5 features compose: arrival-batched FFN (ep=4
    default) + sorted-return combine.  All remote returns issue at the
    final grid step, immediately before the drain's row waits and the
    segment-sum — the tightest schedule the combine's semaphore
    accounting has to survive.  Race detector on."""
    monkeypatch.setenv("FLASHMOE_FUSED_COMBINE", "1")
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=512,
                    capacity_factor=1.0, drop_tokens=True, ep=4, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    got = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                             detect_races=True)
    want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want.out), rtol=2e-4, atol=2e-4
    )


def _assert_fused_grads_match_collective(params, x, cfg, mesh):
    """Shared gradient contract: jitted grads (un-jitted grad through
    the fused kernels can deadlock the interpreter — see the note on
    the combine gradient test) compared param-by-param."""
    def loss_fused(p, xx):
        o = fused_ep_moe_layer(p, xx, cfg, mesh, interpret=True)
        return (o.out.astype(jnp.float32) ** 2).sum()

    def loss_coll(p, xx):
        o = ep_moe_layer(p, xx, cfg, mesh, use_pallas=False)
        return (o.out.astype(jnp.float32) ** 2).sum()

    gf = jax.jit(jax.grad(loss_fused, argnums=(0, 1)))(params, x)
    gc = jax.jit(jax.grad(loss_coll, argnums=(0, 1)))(params, x)
    np.testing.assert_allclose(np.asarray(gf[1]), np.asarray(gc[1]),
                               rtol=5e-3, atol=5e-3)
    for k in gc[0]:
        np.testing.assert_allclose(
            np.asarray(gf[0][k]), np.asarray(gc[0][k]),
            rtol=5e-3, atol=5e-3, err_msg=k,
        )


@pytest.mark.slow
def test_fused_batched_gradients(monkeypatch, devices):
    """Autodiff through the batched-schedule forward (the custom VJP's
    backward is schedule-independent, but the fwd kernel under
    linearize is not)."""
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    monkeypatch.delenv("FLASHMOE_FUSED_COMBINE", raising=False)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=4, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    _assert_fused_grads_match_collective(params, x, cfg, mesh)


@pytest.mark.slow
def test_fused_batched_forced_at_two_ranks(monkeypatch, tmp_path,
                                           devices):
    """ep=2 sits below the batched default (the schedules tie on weight
    bytes there) but a measured `batched: true` tuning entry must force
    it — the single-remote-source edge of the generalized two-pass
    (first_q=1, n_srcs=1)."""
    import json

    from flashmoe_tpu import tuning

    p = tmp_path / "t.json"
    p.write_text(json.dumps({"generation": "x", "entries": [{
        "kernel": "fused_ep", "match": {"h": 128},
        "set": {"batched": True}}]}))
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(p))
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    tuning._load.cache_clear()
    try:
        cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                        intermediate_size=256, sequence_len=256,
                        drop_tokens=False, ep=2, **F32)
        params, x = _setup(cfg)
        mesh = make_mesh(cfg, dp=1, devices=devices[:2])
        out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(np.asarray(out.out), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
    finally:
        tuning._load.cache_clear()


@pytest.mark.slow
def test_fused_combine_gradients_match_collective_path(monkeypatch,
                                                       devices):
    """Router + FFN + input gradients must flow correctly through the
    in-kernel combine's custom VJP (w_sorted scatter-transpose + sorted
    dy reconstruction), matching autodiff through the collective path —
    including drops, where unoccupied sorted rows hold garbage that must
    not leak into any cotangent.

    The grads are jitted: un-jitted ``jax.grad`` (eager
    direct_linearize) deadlocks the Pallas interpreter's vector-clock
    device barrier when executing this kernel's forward — a jax
    interpreter issue (a jax.Array leaks into the numpy clock store and
    np.maximum defers back into a blocked dispatch); ``jit(grad(...))``
    compiles the same program and runs clean."""
    monkeypatch.setenv("FLASHMOE_FUSED_COMBINE", "1")
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    _assert_fused_grads_match_collective(params, x, cfg, mesh)


@pytest.mark.slow
def test_fused_custom_src_order_any_permutation(devices):
    """Correctness must never depend on the source-processing schedule:
    an adversarial src_order (own slab first, then reverse ring — the
    WORST static prediction) must still match the oracle, with the
    race detector on (the waits, not the order, enforce the protocol)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=4, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    d = 4
    order = np.stack([
        np.array([r] + [(r - s) % d for s in range(1, d)], np.int32)
        for r in range(d)
    ])
    out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                             detect_races=True, src_order=order)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def _force_tiles(monkeypatch, tmp_path, cm, kw, h=128):
    """Pin the rowwin (cm, kw) pair through a throwaway fused_tiles
    table (the mechanism tune_sweep/bench --tiles force candidates
    with)."""
    import json

    from flashmoe_tpu import tuning

    p = tmp_path / "tiles.json"
    p.write_text(json.dumps({"generation": "test", "entries": [{
        "kernel": "fused_tiles", "match": {"h": h},
        "set": {"cm": cm, "kw": kw}}]}))
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(p))
    tuning._load.cache_clear()


# The interpret-mode DMA/semaphore emulation this file's kernel tests
# need is absent in some jax versions (the suite's documented 8
# pre-existing environment failures).  NEW kernel-launch tests skip on
# that gap instead of adding to it; the schedule algebra stays gated by
# the emulation test below, which needs no kernel.
from jax.experimental.pallas import tpu as _pltpu  # noqa: E402

requires_interpret = pytest.mark.skipif(
    not hasattr(_pltpu, "InterpretParams"),
    reason="TPU interpret mode unavailable in this jax (pre-existing "
           "environment gap; see ROADMAP.md suite trajectory)")


@requires_interpret
@pytest.mark.parametrize("ep", [1, 2, 4])
def test_rowwin_matches_oracle(ep, monkeypatch, tmp_path, devices):
    """The row-windowed schedule (ISSUE 12) across world sizes — forced
    multi-window (kw=64 -> 4 K-windows, cm=32 -> multiple row tiles) so
    the HBM partial-sum accumulator path is really exercised — must
    match the dense oracle, with the race detector on."""
    from flashmoe_tpu import tuning

    _force_tiles(monkeypatch, tmp_path, cm=32, kw=64)
    try:
        cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                        intermediate_size=256, sequence_len=256,
                        drop_tokens=False, ep=ep,
                        fused_schedule="rowwin", **F32)
        params, x = _setup(cfg)
        mesh = make_mesh(cfg, dp=1, devices=devices[:ep])
        out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True,
                                 detect_races=True)
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
        )
    finally:
        tuning._load.cache_clear()


@requires_interpret
@pytest.mark.parametrize("other", ["stream", "batched", "collective"])
@pytest.mark.slow
def test_rowwin_identity_across_schedules(other, monkeypatch, tmp_path,
                                          devices):
    """ISSUE 12 acceptance: rowwin output vs every mutually-feasible
    alternative on the same shape — BIT-identical against the stream
    schedule when the tile/window geometry matches (identical f32
    partial-sum order: acc = sum_j act(x @ Wup_j) @ Wdn_j, the HBM
    round-trip preserves f32 exactly), allclose against the batched
    schedule and the collective path (different accumulation
    geometry reassociates float adds).  Drops included."""
    from flashmoe_tpu import tuning

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=512,
                    capacity_factor=1.0, drop_tokens=True, ep=4, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    # rowwin at (cm=32, kw=64): 4 windows x multiple row tiles
    _force_tiles(monkeypatch, tmp_path, cm=32, kw=64)
    try:
        rw = fused_ep_moe_layer(params, x,
                                cfg.replace(fused_schedule="rowwin"),
                                mesh, interpret=True, detect_races=True)
        if other == "collective":
            want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
            np.testing.assert_allclose(
                np.asarray(rw.out), np.asarray(want.out),
                rtol=2e-4, atol=2e-4)
            np.testing.assert_array_equal(
                np.asarray(rw.expert_counts),
                np.asarray(want.expert_counts))
        elif other == "batched":
            got = fused_ep_moe_layer(
                params, x, cfg.replace(fused_schedule="batched"), mesh,
                interpret=True)
            np.testing.assert_allclose(np.asarray(rw.out),
                                       np.asarray(got.out),
                                       rtol=1e-5, atol=1e-5)
        else:
            # stream at the SAME (cm, bi=kw) tiles: identical chunked
            # f32 accumulation order -> bit-identical
            import json

            p = tmp_path / "stream.json"
            p.write_text(json.dumps({"generation": "test", "entries": [{
                "kernel": "fused_ep", "match": {"h": 128},
                "set": {"cm": 32, "bi_cap": 64}}]}))
            monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(p))
            tuning._load.cache_clear()
            got = fused_ep_moe_layer(
                params, x, cfg.replace(fused_schedule="stream"), mesh,
                interpret=True)
            np.testing.assert_array_equal(np.asarray(rw.out),
                                          np.asarray(got.out))
    finally:
        tuning._load.cache_clear()


def test_rowwin_window_major_emulation():
    """Schedule-math gate that needs no kernel execution (the interpret
    gap of this environment's jax must not leave the rowwin algebra
    unasserted): emulate the window-major loop — per K-window compute
    hidden_j = act(x @ Wup_j), fold acc += hidden_j @ Wdn_j through an
    f32 round-trip buffer (the HBM accumulator) — and assert BIT
    equality with the stream schedule's chunked accumulation and exact
    closeness to the unchunked einsum."""
    import numpy as np

    rng = np.random.RandomState(0)
    cm, h, i, kw = 32, 64, 256, 64
    x = rng.randn(cm, h).astype(np.float32)
    wu = rng.randn(h, i).astype(np.float32)
    wd = rng.randn(i, h).astype(np.float32)

    def relu(v):
        return np.maximum(v, 0.0)

    # stream schedule: VMEM-resident f32 acc over K-chunks
    acc_stream = np.zeros((cm, h), np.float32)
    for j in range(i // kw):
        hid = relu(x @ wu[:, j * kw:(j + 1) * kw])
        acc_stream += hid @ wd[j * kw:(j + 1) * kw, :]

    # rowwin schedule: the SAME per-window algebra, but the partial sum
    # round-trips through an f32 "HBM" buffer between windows
    hbm = None
    for j in range(i // kw):
        acc = np.zeros((cm, h), np.float32) if j == 0 else hbm.copy()
        hid = relu(x @ wu[:, j * kw:(j + 1) * kw])
        acc += hid @ wd[j * kw:(j + 1) * kw, :]
        hbm = acc.astype(np.float32)  # f32 -> f32: exact
    np.testing.assert_array_equal(hbm, acc_stream)
    # and both are the chunked form of the plain GEMM chain
    dense = relu(x @ wu) @ wd
    np.testing.assert_allclose(hbm, dense, rtol=1e-5, atol=1e-4)


def test_forced_infeasible_schedule_raises():
    """MoEConfig.fused_schedule pins a schedule past the heuristics but
    never past the VMEM gate: forcing a weights-once schedule onto a
    mixtral-width expert (or rowwin onto an absurd hidden size) must
    raise a clear ValueError at resolution — the planner marks the
    matching row infeasible instead (tests/test_planner.py)."""
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.parallel.fused import schedule_table

    mix = BENCH_CONFIGS["mixtral"]
    with pytest.raises(ValueError, match="VMEM-infeasible"):
        from flashmoe_tpu.parallel.fused import (
            _fused_schedule, _resolve_tiles,
        )

        cm, bi = _resolve_tiles(160, 4096, 14336, "bfloat16", False)
        _fused_schedule(160, 4096, 14336, 2, True, cm, bi, False, 2, 8,
                        {}, dtype_name="bfloat16", forced="batched")
    # schedule_table never raises for planner consumers: the forced
    # infeasibility surfaces as a reason + auto fallback
    t = schedule_table(mix.replace(fused_schedule="batched"), 8)
    assert t["forced_infeasible"] and "VMEM" in t["forced_infeasible"]
    assert t["schedule"] == "rowwin"  # the auto choice stands in
    # an absurd hidden size starves even the minimal rowwin window pair
    from flashmoe_tpu.parallel.fused import _rowwin_tiles

    assert _rowwin_tiles(32, 2 ** 17, 2 ** 17, 4, None, False, False,
                         2) == (None, None)


def test_rowwin_respects_batched_kill_switches(monkeypatch):
    """rowwin is a batched-pass schedule: FLASHMOE_FUSED_BATCHED=0 (a
    request for per-source arrival processing) must suppress the AUTO
    rowwin choice too, while FLASHMOE_FUSED_ROWWIN=0 targets it
    individually and an explicit fused_schedule='rowwin' forces past
    both."""
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.parallel.fused import schedule_table

    mix = BENCH_CONFIGS["mixtral"]
    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    monkeypatch.delenv("FLASHMOE_FUSED_ROWWIN", raising=False)
    assert schedule_table(mix, 8)["schedule"] == "rowwin"
    monkeypatch.setenv("FLASHMOE_FUSED_ROWWIN", "0")
    assert schedule_table(mix, 8)["schedule"] == "stream"
    monkeypatch.delenv("FLASHMOE_FUSED_ROWWIN")
    monkeypatch.setenv("FLASHMOE_FUSED_BATCHED", "0")
    assert schedule_table(mix, 8)["schedule"] == "stream"
    assert schedule_table(mix.replace(fused_schedule="rowwin"),
                          8)["schedule"] == "rowwin"


def test_arrival_order_and_skew_bounds():
    """The static arrival-order schedule (VERDICT r3 missing #2): on a
    homogeneous torus it reduces to ring order; rows are always own-first
    permutations; and across the committed skew experiment the predicted
    order recovers the oracle makespan while ring order's stall stays
    bounded by the arrival spread."""
    import importlib.util as ilu
    import os
    from flashmoe_tpu.parallel.topology import arrival_order
    spec = ilu.spec_from_file_location(
        "skew_sim", os.path.join(os.path.dirname(__file__), "..",
                                 "scripts", "skew_sim.py"))
    sim = ilu.module_from_spec(spec)
    spec.loader.exec_module(sim)
    run, torus_adj = sim.run, sim.torus_adj

    adj = torus_adj(8)
    order = arrival_order(adj, 4.0)
    for r in range(8):
        assert order[r, 0] == r
        assert sorted(order[r]) == list(range(8))
    ring = np.array([[(r + s) % 8 for s in range(8)] for r in range(8)])
    np.testing.assert_array_equal(order, ring)

    for row in run(8, slab_mb=4.0, t_c=0.3):
        # perfect estimate -> predicted order is arrival order
        assert row["pred_stall_ms"] <= 1e-9, row
        # one slow link stalls ring order at most one arrival spread
        assert row["ring_stall_ms"] <= row["arrival_spread_ms"] + 1e-9, row
