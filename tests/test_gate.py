"""Fused Pallas gate kernel vs the XLA router (interpreter mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.ops.gate import router_pallas, router_xla


def _inputs(cfg, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    x = jax.random.normal(k1, (cfg.tokens, cfg.hidden_size), jnp.float32)
    w = jax.random.normal(k2, (cfg.hidden_size, cfg.num_experts), jnp.float32)
    return x, w / jnp.sqrt(cfg.hidden_size)


@pytest.mark.parametrize("cfg", [
    MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128, sequence_len=128),
    MoEConfig(num_experts=64, expert_top_k=4, hidden_size=256, sequence_len=256),
    MoEConfig(num_experts=200, expert_top_k=6, hidden_size=128,
              sequence_len=128),  # E > 128: padded lane dim
    MoEConfig(num_experts=8, expert_top_k=1, hidden_size=128, sequence_len=128),
], ids=["e8k2", "e64k4", "e200k6", "e8k1"])
def test_pallas_matches_xla(cfg):
    x, w = _inputs(cfg)
    want = router_xla(x, w, cfg)
    got = router_pallas(x, w, cfg, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(got.expert_idx), np.asarray(want.expert_idx)
    )
    np.testing.assert_allclose(
        np.asarray(got.combine_weights), np.asarray(want.combine_weights),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_array_equal(
        np.asarray(got.expert_counts), np.asarray(want.expert_counts)
    )
    np.testing.assert_allclose(
        np.asarray(got.probs_mean), np.asarray(want.probs_mean),
        rtol=1e-5, atol=1e-7,
    )
    np.testing.assert_allclose(
        float(got.aux_loss), float(want.aux_loss), rtol=1e-5
    )


def test_zloss():
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    sequence_len=128, router_z_loss_coef=0.1)
    x, w = _inputs(cfg)
    want = router_xla(x, w, cfg)
    got = router_pallas(x, w, cfg, interpret=True)
    np.testing.assert_allclose(
        float(got.z_loss), float(want.z_loss), rtol=1e-4
    )
    assert float(got.z_loss) > 0


def test_counts_sum_to_sk():
    cfg = MoEConfig(num_experts=16, expert_top_k=3, hidden_size=64,
                    sequence_len=128)
    x, w = _inputs(cfg)
    got = router_pallas(x, w, cfg, interpret=True)
    assert int(jnp.sum(got.expert_counts)) == cfg.tokens * cfg.expert_top_k
    # weights normalized per token
    np.testing.assert_allclose(
        np.asarray(jnp.sum(got.combine_weights, axis=-1)),
        np.ones(cfg.tokens), rtol=1e-5,
    )


def test_tiled_gate_matches_xla_large_e():
    """The two-pass expert-tiled gate (the reference's multi-block ring,
    gate.cuh:93-467, as grid-streamed online softmax + top-k merge):
    every RouterOutput field must match the XLA oracle for E spanning
    multiple expert tiles, including a DeepSeek-style top-6."""
    from flashmoe_tpu.ops.gate import router_pallas_tiled

    for e, k in ((1280, 2), (600, 6)):
        cfg = MoEConfig(num_experts=e, expert_top_k=k, hidden_size=128,
                        intermediate_size=256, is_training=True,
                        dtype=jnp.float32, param_dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (64, 128),
                              jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (128, e),
                              jnp.float32) * 0.1
        got = router_pallas_tiled(x, w, cfg, interpret=True)
        want = router_xla(x, w, cfg)
        np.testing.assert_array_equal(np.asarray(got.expert_idx),
                                      np.asarray(want.expert_idx))
        np.testing.assert_allclose(
            np.asarray(got.combine_weights),
            np.asarray(want.combine_weights), rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(got.expert_counts),
                                      np.asarray(want.expert_counts))
        np.testing.assert_allclose(np.asarray(got.probs_mean),
                                   np.asarray(want.probs_mean),
                                   rtol=1e-5, atol=1e-8)
        np.testing.assert_allclose(float(got.aux_loss),
                                   float(want.aux_loss), rtol=1e-5)


def test_router_dispatches_tiled_beyond_vmem_budget():
    """router() must route large-E configs to the tiled kernel (not the
    XLA fallback) and stay differentiable through it."""
    from flashmoe_tpu.ops import gate as gate_mod

    e = 16384
    cfg = MoEConfig(num_experts=e, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, is_training=True,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    assert gate_mod.gate_vmem_bytes(64, 128, e, jnp.float32) \
        > gate_mod._GATE_VMEM_BUDGET
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (128, e),
                          jnp.float32) * 0.1

    calls = {}
    orig = gate_mod.router_pallas_tiled

    def spy(*a, **kw):
        calls["tiled"] = True
        return orig(*a, **kw)

    gate_mod.router_pallas_tiled = spy
    try:
        got = gate_mod.router(x, w, cfg, use_pallas=True, interpret=True)
        want = router_xla(x, w, cfg)
        np.testing.assert_array_equal(np.asarray(got.expert_idx),
                                      np.asarray(want.expert_idx))

        def loss(w_):
            r = gate_mod.router(x, w_, cfg, use_pallas=True,
                                interpret=True)
            return (r.combine_weights.sum() + r.aux_loss).astype(
                jnp.float32)

        g = jax.grad(loss)(w)
        gx = jax.grad(lambda w_: (router_xla(x, w_, cfg).combine_weights
                                  .sum()
                                  + router_xla(x, w_, cfg).aux_loss
                                  ).astype(jnp.float32))(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gx),
                                   rtol=1e-4, atol=1e-6)
    finally:
        gate_mod.router_pallas_tiled = orig
    assert calls.get("tiled")


def test_tiled_gate_inference_skips_stats():
    """At inference (no aux/z consumers) the tiled gate runs pass 1 only
    — no logits spill, no stats pass — while routing decisions, weights
    and selection counts still match the oracle exactly."""
    from flashmoe_tpu.ops.gate import router_pallas_tiled

    cfg = MoEConfig(num_experts=1280, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, is_training=False,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (128, 1280),
                          jnp.float32) * 0.1
    got = router_pallas_tiled(x, w, cfg, interpret=True)
    want = router_xla(x, w, cfg)
    np.testing.assert_array_equal(np.asarray(got.expert_idx),
                                  np.asarray(want.expert_idx))
    np.testing.assert_allclose(np.asarray(got.combine_weights),
                               np.asarray(want.combine_weights),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(got.expert_counts),
                                  np.asarray(want.expert_counts))
    assert float(got.aux_loss) == 0.0 and float(got.z_loss) == 0.0
