"""KV-cache generation: consistency with the training-path forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.generate import generate
from flashmoe_tpu.models.transformer import forward, init_params

CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=64, num_layers=2,
                moe_frequency=2, vocab_size=256, num_heads=2,
                drop_tokens=False, dtype=jnp.float32,
                param_dtype=jnp.float32)


@pytest.mark.slow
def test_greedy_matches_full_forward():
    """Greedy decode must reproduce argmax of the full (non-cached)
    forward at every step."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 256)
    out = generate(params, prompt, CFG, max_new_tokens=4)
    assert out.shape == (2, 12)

    # oracle: re-run the full forward on the growing sequence
    seq = prompt
    for _ in range(4):
        logits, _ = forward(params, seq, CFG)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_sampled_decode_shape_and_range():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 256)
    out = generate(params, prompt, CFG, max_new_tokens=8, temperature=1.0,
                   key=jax.random.PRNGKey(3))
    assert out.shape == (1, 12)
    toks = np.asarray(out)
    assert (toks >= 0).all() and (toks < 256).all()
