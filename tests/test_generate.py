"""KV-cache generation: consistency with the training-path forward,
the two prefill arms, sampling truncations, and stop tokens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.generate import (
    _decode_step, generate, init_cache, prefill_batched, prefill_loop,
    sample_tokens,
)
from flashmoe_tpu.models.transformer import forward, init_params

CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=64, num_layers=2,
                moe_frequency=2, vocab_size=256, num_heads=2,
                drop_tokens=False, dtype=jnp.float32,
                param_dtype=jnp.float32)


@pytest.mark.slow
def test_greedy_matches_full_forward():
    """Greedy decode must reproduce argmax of the full (non-cached)
    forward at every step."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 256)
    out = generate(params, prompt, CFG, max_new_tokens=4)
    assert out.shape == (2, 12)

    # oracle: re-run the full forward on the growing sequence
    seq = prompt
    for _ in range(4):
        logits, _ = forward(params, seq, CFG)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_sampled_decode_shape_and_range():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 256)
    out = generate(params, prompt, CFG, max_new_tokens=8, temperature=1.0,
                   key=jax.random.PRNGKey(3))
    assert out.shape == (1, 12)
    toks = np.asarray(out)
    assert (toks >= 0).all() and (toks < 256).all()


def test_batched_prefill_logits_equal_loop():
    """Satellite: the single-pass prefill and the one-token-at-a-time
    loop are logits-equal (and cache-equal) on dropless configs."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 256)
    lb, cb = prefill_batched(params, CFG, prompt, init_cache(CFG, 2, 8))
    ll, cl = prefill_loop(params, CFG, prompt, init_cache(CFG, 2, 8))
    np.testing.assert_allclose(np.asarray(lb), np.asarray(ll),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cb.k), np.asarray(cl.k),
                               rtol=0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cb.v), np.asarray(cl.v),
                               rtol=0, atol=1e-5)
    # and the full decode agrees token-for-token across the two arms
    out_b = generate(params, prompt, CFG, max_new_tokens=4,
                     prefill="batched")
    out_l = generate(params, prompt, CFG, max_new_tokens=4,
                     prefill="loop")
    np.testing.assert_array_equal(np.asarray(out_b), np.asarray(out_l))


def test_prefill_auto_and_validation():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 4), 0, 256)
    with pytest.raises(ValueError, match="prefill"):
        generate(params, prompt, CFG, max_new_tokens=2,
                 prefill="bogus")


def test_teacher_forcing_decode_matches_forward():
    """Satellite: step-wise decode logits pin against the full-sequence
    training forward on the SAME tokens — the equivalence nothing
    previously asserted between ``_decode_step`` and
    ``transformer.forward``."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 10), 0, 256)
    full, _ = forward(params, tokens, CFG)          # [B, T, V]

    cache = init_cache(CFG, 2, 10)
    step_logits = []
    for i in range(10):
        x = params["embed"].astype(CFG.dtype)[tokens[:, i]][:, None, :]
        lg, cache = _decode_step(params, CFG, x, cache, jnp.int32(i))
        step_logits.append(lg)
    stepwise = jnp.stack(step_logits, axis=1)       # [B, T, V]
    np.testing.assert_allclose(np.asarray(stepwise), np.asarray(full),
                               rtol=0, atol=2e-5)


def test_sample_tokens_truncations():
    """top-k=1 is argmax at any temperature; top-p -> 0 keeps only the
    head; truncations never emit a masked token."""
    key = jax.random.PRNGKey(0)
    logits = jax.random.normal(key, (4, 32), jnp.float32) * 3.0
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    np.testing.assert_array_equal(
        np.asarray(sample_tokens(logits, key, temperature=0.0)), greedy)
    np.testing.assert_array_equal(
        np.asarray(sample_tokens(logits, key, temperature=1.3,
                                 top_k=1)), greedy)
    np.testing.assert_array_equal(
        np.asarray(sample_tokens(logits, key, temperature=0.9,
                                 top_p=1e-6)), greedy)
    # top-k=3: every draw must come from the 3 highest logits
    top3 = np.argsort(np.asarray(logits), axis=-1)[:, -3:]
    for s in range(5):
        draw = np.asarray(sample_tokens(
            logits, jax.random.PRNGKey(s), temperature=1.0, top_k=3))
        for b in range(4):
            assert draw[b] in top3[b]
    with pytest.raises(ValueError, match="top_p"):
        sample_tokens(logits, key, temperature=1.0, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        sample_tokens(logits, key, temperature=1.0, top_k=-1)


def test_stop_tokens_freeze_rows():
    """A row that emits a stop token pads the rest of its output while
    other rows keep decoding (per-request retirement semantics)."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0, 256)
    base = np.asarray(generate(params, prompt, CFG, max_new_tokens=6))
    stop = int(base[0, 4])                          # row 0's 1st token
    out = np.asarray(generate(params, prompt, CFG, max_new_tokens=6,
                              stop_tokens=(stop,), pad_token=0))
    assert out[0, 4] == stop
    assert (out[0, 5:] == 0).all()                  # frozen after stop
    if stop not in base[1, 4:]:
        np.testing.assert_array_equal(out[1], base[1])  # unaffected
