"""The external fenced lease store + sub-step heartbeat watchdog
(PR 19): the pieces that make the front-door cluster survive REAL
process boundaries.

Fast lanes exercise the store file directly — strictly-newer epoch
fencing (a zombie's re-assert refused with a ``frontdoor.fence``
decision), torn-tail recovery including a genuine ``kill -9`` of a
writer mid-append (the kernel releases the flock, the next writer
truncates the garbage), monotonic heartbeat sequencing, and the
watchdog's deadline hysteresis (a slow-but-alive replica that beats
every other observation is NEVER declared stalled; a hung one is
declared after exactly ``misses_to_stall`` consecutive misses).

The slow lane is the cross-OS-process drill the ISSUE demands: a real
``doorproc`` child process sharing ONLY the store file with the
parent's fabric (tcp socket wire, heartbeats armed), one door failed
over AND one decode replica killed, token-bit-equal output, zero
orphan spans, and the child's stale-epoch refusal visible in the
merged fleet telemetry.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from flashmoe_tpu.fabric.leasestore import (
    HeartbeatConfig, HeartbeatWatchdog, LeaseStore, StaleLeaseError,
)
from flashmoe_tpu.utils.telemetry import Metrics


@pytest.fixture()
def store(tmp_path):
    return LeaseStore(str(tmp_path / "leases.bin"),
                      metrics_obj=Metrics(), peer=0)


# ----------------------------------------------------------------------
# epoch fencing
# ----------------------------------------------------------------------

def test_lease_fencing_strictly_newer(store):
    store.init_leases({0: 0, 1: 1})
    assert store.leases()[0].epoch == 0
    lease = store.write_lease(0, 1, 1, reason="failover")
    assert (lease.owner, lease.epoch) == (1, 1)
    # equal epoch is STALE — strictly-newer is the fencing rule, so a
    # zombie replaying the same token it just lost with cannot win
    with pytest.raises(StaleLeaseError, match="fenced off"):
        store.write_lease(0, 0, 1, reason="zombie_reassert")
    # and so is anything older
    with pytest.raises(StaleLeaseError):
        store.write_lease(0, 0, 0)
    assert store.fenced == 2
    table = store.leases()
    assert (table[0].owner, table[0].epoch) == (1, 1)   # unclobbered


def test_fence_decision_names_the_zombie(store):
    store.init_leases({3: 1})
    store.write_lease(3, 0, 2, reason="failover")
    with pytest.raises(StaleLeaseError):
        store.write_lease(3, 1, 2, reason="zombie_reassert")
    fences = [d for d in store.metrics.decisions
              if d["decision"] == "frontdoor.fence"]
    assert len(fences) == 1
    f = fences[0]
    assert f["shard"] == 3 and f["refused"] is True
    assert f["claimant"] == 1 and f["stale_epoch"] == 2
    assert f["current_epoch"] == 2 and f["current_owner"] == 0
    assert f["reason"] == "zombie_reassert"


def test_init_leases_adopts_live_table(store):
    """A second process joining an existing store must NOT reset it."""
    store.init_leases({0: 0, 1: 1})
    store.write_lease(1, 0, 5, reason="failover")
    joiner = LeaseStore(store.path, metrics_obj=Metrics(), peer=1)
    joiner.init_leases({0: 1, 1: 1, 2: 1})      # 0/1 exist, 2 is new
    table = joiner.leases()
    assert (table[0].owner, table[0].epoch) == (0, 0)
    assert (table[1].owner, table[1].epoch) == (0, 5)
    assert (table[2].owner, table[2].epoch) == (1, 0)


# ----------------------------------------------------------------------
# torn-write recovery
# ----------------------------------------------------------------------

def test_torn_tail_skipped_on_read_repaired_on_write(store):
    store.init_leases({0: 0})
    store.write_lease(0, 1, 1, reason="survives")
    store.write_lease(0, 0, 2, reason="the victim")
    torn = store.tear_last_record()
    assert torn > 0
    # readers never see the half-written epoch 2 — and read() leaves
    # the repair to the next WRITER
    assert store.leases()[0].epoch == 1
    assert store.repairs == 0
    store.write_lease(0, 1, 2, reason="post_crash")
    assert store.repairs == 1
    reps = [d for d in store.metrics.decisions
            if d["decision"] == "frontdoor.lease_repair"]
    assert len(reps) == 1
    assert reps[0]["torn_bytes"] == torn
    assert reps[0]["restored_epoch"] == 1
    table = store.leases()
    assert (table[0].owner, table[0].epoch) == (1, 2)


_KILLER = textwrap.dedent("""\
    import os, signal, sys
    from flashmoe_tpu.fabric.leasestore import LeaseStore

    store = LeaseStore(sys.argv[1], metrics_obj=None, peer=9)

    class Die(Exception):
        pass

    real_write = LeaseStore._write

    def half_write_then_die(self, fh, state):
        # emulate the kernel yanking the process mid-append: flush
        # HALF the frame while still holding the flock, then SIGKILL
        # ourselves — no unlock, no truncate, no atexit.
        import flashmoe_tpu.fabric.leasestore as L
        frame = L._frame(state)
        fh.seek(0, os.SEEK_END)
        fh.write(frame[: len(frame) // 2])
        fh.flush()
        os.fsync(fh.fileno())
        os.kill(os.getpid(), signal.SIGKILL)

    LeaseStore._write = half_write_then_die
    store.write_lease(0, 9, 99, reason="doomed")
    """)


def test_kill9_mid_append_recovers(store):
    """A real writer process SIGKILLed mid-append through the actual
    ``write_lease`` path: the survivor sees the pre-crash table, is
    not deadlocked by the dead writer's flock (the kernel released
    it), and the next write rolls the torn tail back."""
    store.init_leases({0: 0})
    store.write_lease(0, 1, 1, reason="pre_crash")
    before = os.path.getsize(store.path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _KILLER, store.path],
        capture_output=True, text=True, timeout=120, env=env)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    assert os.path.getsize(store.path) > before     # garbage landed
    # the flock died with the writer: this read would hang forever if
    # the kernel had not released it
    assert store.leases()[0].epoch == 1             # 99 never existed
    store.write_lease(0, 0, 2, reason="post_crash")
    assert store.repairs == 1
    assert store.leases()[0].epoch == 2


# ----------------------------------------------------------------------
# heartbeats + watchdog hysteresis
# ----------------------------------------------------------------------

def test_heartbeat_seq_is_monotonic(store):
    assert store.heartbeat(0, 5, ts_ms=1.0, phase="decode", step=2)
    assert not store.heartbeat(0, 5, ts_ms=2.0)     # replay dropped
    assert not store.heartbeat(0, 4, ts_ms=3.0)     # regression dropped
    assert store.heartbeat(0, 6, ts_ms=4.0, phase="end", step=2)
    row = store.beats()["0"]
    assert row["seq"] == 6 and row["phase"] == "end"
    assert row["ts_ms"] == 4.0 and row["step"] == 2


def test_heartbeat_config_validates():
    with pytest.raises(ValueError, match="misses_to_stall"):
        HeartbeatConfig(misses_to_stall=0)
    assert HeartbeatConfig().misses_to_stall >= 2   # hysteresis default


def test_watchdog_slow_replica_never_false_positives(store):
    """The no-false-positive gate: a replica beating every OTHER
    observation keeps resetting its miss count and is never declared
    stalled, no matter how long the run."""
    mx = Metrics()
    wd = HeartbeatWatchdog(store, misses_to_stall=2, tick_ms=1.0,
                           metrics_obj=mx)
    seq = 0
    for step in range(20):
        if step % 2 == 0:               # slow: beats on even steps only
            seq += 1
            store.heartbeat(7, seq)
        assert wd.observe(step, [7], pending=lambda r: True) == []
    assert wd.stalled_total == 0
    assert not [d for d in mx.decisions
                if d["decision"] == "fabric.heartbeat_stall"]
    # it DID take misses — hysteresis absorbed them
    misses = [d for d in mx.decisions
              if d["decision"] == "fabric.heartbeat_miss"]
    assert misses and all(m["misses"] == 1 for m in misses)


def test_watchdog_declares_stall_after_exact_hysteresis(store):
    mx = Metrics()
    wd = HeartbeatWatchdog(store, misses_to_stall=3, tick_ms=0.5,
                           metrics_obj=mx)
    store.heartbeat(4, 1, phase="prefill", step=0)
    assert wd.observe(0, [4], pending=lambda r: True) == []  # fresh
    assert wd.observe(1, [4], pending=lambda r: True) == []  # miss 1
    assert wd.observe(2, [4], pending=lambda r: True) == []  # miss 2
    assert wd.observe(3, [4], pending=lambda r: True) == [4]  # stalled
    stalls = [d for d in mx.decisions
              if d["decision"] == "fabric.heartbeat_stall"]
    assert len(stalls) == 1
    s = stalls[0]
    assert s["replica"] == 4 and s["misses"] == 3
    assert s["detect_ms"] == pytest.approx(1.5)     # 3 misses x 0.5 ms
    assert s["last_phase"] == "prefill"             # WHERE it froze


def test_watchdog_idle_replica_owes_no_beat(store):
    """Miss accounting is gated on pending work: an idle replica that
    never beats is not a stall candidate."""
    wd = HeartbeatWatchdog(store, misses_to_stall=1, tick_ms=1.0,
                           metrics_obj=Metrics())
    for step in range(5):
        assert wd.observe(step, [2], pending=lambda r: False) == []
    assert wd.stalled_total == 0


# ----------------------------------------------------------------------
# the cross-OS-process drill
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_cross_process_drill_two_doors_socket_wire(tmp_path,
                                                   monkeypatch):
    """The acceptance drill: door peer 1 is a REAL child process
    (``python -m flashmoe_tpu.fabric.doorproc``) sharing only the
    lease store file with the parent.  The parent drives the fleet
    over the tcp socket wire with heartbeats armed, fails the child's
    door over mid-trace AND kills a decode replica — tokens stay
    bit-equal, no spans orphan, the child is fenced (exit code 3) and
    its stale-epoch refusal shows up in the merged fleet telemetry."""
    import time as _time

    import jax

    from flashmoe_tpu.chaos import FaultPlan
    from flashmoe_tpu.fabric import (
        FrontDoorCluster, HandoffTransport, ServingFabric, VirtualClock,
    )
    from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.observe import merge_report
    from flashmoe_tpu.serving.engine import ServeConfig, ServingEngine
    from flashmoe_tpu.serving.loadgen import build_requests, tiny_config

    cfg = tiny_config()
    serve = ServeConfig(max_batch=2, page_size=8, num_pages=64,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reqs, arrivals = build_requests(6, vocab=cfg.vocab_size,
                                    prompt_len=8, max_new=4, seed=0,
                                    arrival_every=1)
    eng = ServingEngine(params, cfg, serve, metrics_obj=Metrics())
    baseline = eng.run(reqs, arrivals)
    eng.close()

    monkeypatch.setenv(ENV_MOCK_FABRIC, "2")
    store_path = str(tmp_path / "leases.bin")
    child_shard = str(tmp_path / "telemetry.door1.jsonl")
    parent_shard = str(tmp_path / "telemetry.door0.jsonl")

    mx = Metrics()
    store = LeaseStore(store_path, metrics_obj=mx, peer=0)
    transport = HandoffTransport(metrics_obj=mx, wire="tcp")
    fab = ServingFabric(
        params, cfg, serve, metrics_obj=mx, vclock=VirtualClock(),
        transport=transport,
        heartbeat=HeartbeatConfig(misses_to_stall=2,
                                  store_path=store_path),
        fault_plan=FaultPlan("replica_crash", step=3, expert=0))
    cluster = FrontDoorCluster(fab, n_doors=2, n_shards=8,
                               metrics_obj=mx, store=store)

    child = subprocess.Popen(
        [sys.executable, "-m", "flashmoe_tpu.fabric.doorproc",
         "--store", store_path, "--peer", "1",
         "--telemetry", child_shard,
         "--iterations", "2000", "--interval", "0.02"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        # wait for the child's first heartbeat so the failover races a
        # LIVE peer, not a process still importing
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            if "door1" in store.beats():
                break
            _time.sleep(0.05)
        else:
            pytest.fail("doorproc child never heartbeat")

        out = cluster.run(reqs, arrivals, fail_at=2, fail_peer=1)
        errs = cluster.validate()

        child.wait(timeout=60)
    finally:
        if child.poll() is None:
            child.kill()                # kill -9: drill cleanup arm
        child.wait(timeout=30)
        cluster.close()
        fab.close()
        transport.close()

    # tokens bit-equal through door failover + replica crash + tcp wire
    assert sorted(out) == sorted(baseline)
    for rid in baseline:
        assert out[rid] == baseline[rid], f"rid {rid} diverged"
    assert errs == []                   # zero orphan spans
    assert transport.transfers > 0      # KV really crossed the socket

    # the child played the zombie and the store fenced it off
    assert child.returncode == 3, (child.stdout, child.stderr)
    crashes = [d for d in mx.decisions
               if d["decision"] == "fabric.replica_crash"]
    failovers = [d for d in mx.decisions
                 if d["decision"] == "frontdoor.failover"]
    assert len(crashes) == 1 and failovers

    # merged fleet view: both per-door shards, and the child's own
    # telemetry carries the stale-epoch refusal
    with open(parent_shard, "w") as fh:
        for d in mx.decisions:
            fh.write(json.dumps(d, default=str) + "\n")
    rep = merge_report([parent_shard, child_shard])
    assert sorted(rep["hosts"]) == ["door0", "door1"]
    child_recs = [json.loads(line)
                  for line in open(child_shard, encoding="utf-8")]
    child_fences = [r for r in child_recs
                    if r.get("decision") == "frontdoor.fence"]
    assert child_fences and child_fences[0]["refused"] is True
    assert child_fences[0]["peer"] == 1
    assert child_fences[0]["current_epoch"] > child_fences[0][
        "stale_epoch"] - 1              # stale = cached + 1 == current
