"""MoE layer correctness vs the dense-math oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import Activation, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops.moe import moe_layer


def _setup(cfg, seed=0):
    key = jax.random.PRNGKey(seed)
    pk, xk = jax.random.split(key)
    params = init_moe_params(pk, cfg)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), cfg.dtype)
    return params, x


# float32 configs with no token dropping -> optimized path must match oracle
NODROP = dict(drop_tokens=False, dtype=jnp.float32, param_dtype=jnp.float32)


@pytest.mark.parametrize("cfg", [
    MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
              intermediate_size=256, sequence_len=128, **NODROP),
    MoEConfig(num_experts=4, expert_top_k=1, hidden_size=64,
              intermediate_size=128, sequence_len=256, **NODROP),
    MoEConfig(num_experts=16, expert_top_k=4, hidden_size=128,
              intermediate_size=128, sequence_len=128,
              hidden_act=Activation.RELU, **NODROP),
], ids=["top2", "top1", "top4_relu"])
def test_matches_oracle_nodrop(cfg):
    params, x = _setup(cfg)
    want, aux_want = reference_moe(params, x, cfg)
    got = moe_layer(params, x, cfg, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        float(got.aux_loss), float(aux_want) * cfg.aux_loss_coef, rtol=1e-4
    )


def test_gated_ffn_with_shared_experts():
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=128, sequence_len=128, gated_ffn=True,
                    hidden_act=Activation.SILU, num_shared_experts=2, **NODROP)
    params, x = _setup(cfg)
    want, _ = reference_moe(params, x, cfg)
    got = moe_layer(params, x, cfg, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_drop_tokens_capacity():
    """With tight capacity, dropped tokens fall back to (renormalized)
    surviving experts; output stays finite and counts are exact."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=64, sequence_len=128,
                    capacity_factor=0.5, drop_tokens=True,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    params, x = _setup(cfg)
    got = moe_layer(params, x, cfg, use_pallas=False)
    assert np.isfinite(np.asarray(got.out)).all()
    assert int(jnp.sum(got.expert_counts)) == cfg.tokens * cfg.expert_top_k


def test_dense_fallback_e1():
    """E==1 routes through the dense fffn-equivalent path."""
    cfg = MoEConfig(num_experts=1, expert_top_k=1, hidden_size=64,
                    intermediate_size=128, sequence_len=64, **NODROP)
    params, x = _setup(cfg)
    got = moe_layer(params, x, cfg, use_pallas=False)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_integrated_pallas_path_interpret():
    """The fused Pallas gate + grouped-FFN layer end-to-end (interpreter)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=128, **NODROP)
    params, x = _setup(cfg)
    want, _ = reference_moe(params, x, cfg)
    got = moe_layer(params, x, cfg, use_pallas=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("gated,cf", [(False, 1.0), (True, 1.25),
                                      (False, 2.0)],
                         ids=["cf1", "gated_cf1.25", "cf2"])
def test_gather_fused_inference_matches_oracle(gated, cf):
    """The gather-fused capacity path (dispatch built inside the kernel,
    no [E, C, H] HBM buffer) matches the explicit-dispatch XLA oracle."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256,
                    drop_tokens=True, capacity_factor=cf, gated_ffn=gated,
                    dtype=jnp.float32, param_dtype=jnp.float32,
                    is_training=False, gather_fused=True)
    params, x = _setup(cfg)
    got = moe_layer(params, x, cfg, use_pallas=True, interpret=True)
    want = moe_layer(params, x, cfg, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want.out), rtol=2e-4, atol=2e-4
    )


@pytest.mark.parametrize("gated", [False, True], ids=["plain", "gated"])
@pytest.mark.slow
def test_dropless_gather_fused_inference(gated):
    """Dropless inference routes through the gather-fused kernel (inverse
    map from the ragged plan); output and re-gather-VJP grads match XLA."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256,
                    gated_ffn=gated, gather_fused=True, **NODROP)
    params, x = _setup(cfg)
    got = moe_layer(params, x, cfg, use_pallas=True, interpret=True)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    g = jax.grad(lambda xx: moe_layer(params, xx, cfg, use_pallas=True,
                                      interpret=True).out.sum())(x)
    gx = jax.grad(lambda xx: moe_layer(params, xx, cfg,
                                       use_pallas=False).out.sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gx),
                               rtol=5e-3, atol=5e-3)


def test_fused_path_grad_matches_xla_grad():
    """The fused path's custom VJP (pallas fwd, XLA-recompute bwd) must
    produce the same gradients as differentiating the XLA path."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=64, **NODROP)
    params, x = _setup(cfg)

    def loss(p, use_pallas, interpret):
        o = moe_layer(p, x, cfg, use_pallas=use_pallas, interpret=interpret)
        return jnp.sum(o.out ** 2) + o.aux_loss

    gp = jax.grad(lambda p: loss(p, True, True))(params)
    gx = jax.grad(lambda p: loss(p, False, False))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gp),
                    jax.tree_util.tree_leaves(gx)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4
        )


def test_jit_and_grad():
    """The layer must be jittable and differentiable (training path)."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=64, sequence_len=64, is_training=True,
                    **NODROP)
    params, x = _setup(cfg)

    @jax.jit
    def loss_fn(p, x):
        o = moe_layer(p, x, cfg, use_pallas=False)
        return jnp.sum(o.out ** 2) + o.aux_loss

    g = jax.grad(loss_fn)(params, x)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()
