"""Two-stage ICI+DCN transport (VERDICT r4 missing #2 / next #5).

The reference resolves P2P vs remote per peer at init
(``bootstrap.cuh:442-446``) and branches transport at every send
(``os/packet.cuh:221-258``).  The TPU equivalent: when the ep axis spans
slices, the collective path's all-to-all decomposes into an intra-slice
ICI exchange + ONE aggregated DCN message per slice pair
(``parallel/ep.py:_hierarchical_a2a``), selected automatically from the
detected slice blocking (``topology.slice_structure``) the way the
arrival-order schedule is published.  The virtual 8-device CPU mesh
mocks a 2x4 "two-slice" job via ``FLASHMOE_MOCK_SLICES``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.topology import slice_structure

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _setup(cfg, seed=0):
    pk, xk = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(pk, cfg)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), jnp.float32)
    return params, x


def test_hierarchical_a2a_matches_flat_and_oracle(devices):
    """The two-stage exchange is a pure re-decomposition: bit-identical
    routing to the flat all-to-all, oracle-correct output, both
    directions (dispatch and combine-return)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    flat = ep_moe_layer(params, x, cfg, mesh, dcn_inner=0)
    hier = ep_moe_layer(params, x, cfg, mesh, dcn_inner=4)
    np.testing.assert_allclose(np.asarray(hier.out), np.asarray(flat.out),
                               rtol=1e-6, atol=1e-6)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(np.asarray(hier.out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("inner", [2, 4])
def test_hierarchical_a2a_other_factorizations(inner, devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    flat = ep_moe_layer(params, x, cfg, mesh, dcn_inner=0)
    hier = ep_moe_layer(params, x, cfg, mesh, dcn_inner=inner)
    np.testing.assert_allclose(np.asarray(hier.out), np.asarray(flat.out),
                               rtol=1e-6, atol=1e-6)


def test_slice_structure_detection(monkeypatch, devices):
    """Mocked two-slice blocking is detected; single-slice returns
    None; malformed mocks are a clear ValueError naming the world size
    (ISSUE 13 satellite — the pre-hardening guard silently ran the
    flat transport on a mis-typed mock)."""
    monkeypatch.delenv("FLASHMOE_MOCK_SLICES", raising=False)
    assert slice_structure(devices[:8]) is None  # CPU: one process
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    assert slice_structure(devices[:8]) == (2, 4)
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "8")
    assert slice_structure(devices[:8]) == (8, 1)
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "1")
    assert slice_structure(devices[:8]) is None  # explicit single slice
    for bad in ("3", "-2", "0", "banana", "2.5"):
        monkeypatch.setenv("FLASHMOE_MOCK_SLICES", bad)
        with pytest.raises(ValueError, match="8 devices"):
            slice_structure(devices[:8])


def test_bootstrap_publishes_dcn_inner(monkeypatch, devices):
    """An initialized runtime on a mocked 2-slice job publishes
    ranks-per-slice, ep_moe_layer picks it up by default (same pattern
    as the arrival-order table), and the gated accessor refuses meshes
    whose device order differs from jax.devices()."""
    from flashmoe_tpu.runtime import bootstrap

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    monkeypatch.setattr(bootstrap, "_runtime", None)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=8, **F32)
    rt = bootstrap.initialize(cfg, use_decider=False, measure=False)
    try:
        assert rt.dcn_inner == 4
        mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:8])
        assert bootstrap.current_dcn_inner(mesh, 8) == 4
        # permuted mesh: the blocking indexes jax.devices() order
        perm = list(jax.devices()[:8])
        perm[0], perm[1] = perm[1], perm[0]
        mesh_p = make_mesh(cfg, dp=1, devices=perm)
        assert bootstrap.current_dcn_inner(mesh_p, 8) is None
        # end to end: the default path must produce oracle output while
        # riding the published two-stage exchange
        params, x = _setup(cfg)
        out = ep_moe_layer(params, x, cfg, mesh)
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(np.asarray(out.out), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
    finally:
        monkeypatch.setattr(bootstrap, "_runtime", None)


def test_transport_cost_model_prefers_aggregation():
    """The modeled reason the two-stage exchange exists: identical
    cross-slice bytes, inner-times fewer DCN messages — so at MoE slab
    sizes (sub-MB per peer) the alpha savings dominate the extra
    in-slice hop and the hierarchical total wins."""
    from flashmoe_tpu.analysis import a2a_transport_cost

    c = a2a_transport_cost(8, 4, slab_bytes=256 * 1024, gen="v5e")
    assert c["hierarchical"]["dcn_messages"] * 4 == c["flat"]["dcn_messages"]
    assert c["hierarchical"]["total_ms"] < c["flat"]["total_ms"]
    # same bytes must cross DCN either way (aggregation, not elision):
    # beta terms equal once the alpha terms are stripped
    strip = lambda leg, n_msg: leg["dcn_ms"] - n_msg * (10.0 / 1e3)
    np.testing.assert_allclose(
        strip(c["flat"], c["flat"]["dcn_messages"]),
        strip(c["hierarchical"], c["hierarchical"]["dcn_messages"]),
        rtol=1e-9,
    )
    # at very large slabs the extra in-slice traffic can flip the total:
    # the model must expose that crossover rather than hide it
    big = a2a_transport_cost(8, 4, slab_bytes=64 * 2**20, gen="v5e")
    assert big["hierarchical"]["ici_ms"] > big["flat"]["ici_ms"]


# ----------------------------------------------------------------------
# Per-hop wire dtypes (MoEConfig.wire_dtype_dcn, ISSUE 13)
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_dcn_wire_inert_on_flat_and_off_identical(devices):
    """wire_dtype_dcn must be a pure DCN-hop knob: on the flat exchange
    it is inert (bit-identical output), and on the hierarchical
    exchange the default None traces/computes exactly the single-dtype
    path."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    flat = ep_moe_layer(params, x, cfg, mesh, dcn_inner=0)
    flat_knob = ep_moe_layer(params, x,
                             cfg.replace(wire_dtype_dcn="e4m3"),
                             mesh, dcn_inner=0)
    np.testing.assert_array_equal(np.asarray(flat_knob.out),
                                  np.asarray(flat.out))
    hier = ep_moe_layer(params, x, cfg, mesh, dcn_inner=4)
    hier_none = ep_moe_layer(params, x,
                             cfg.replace(wire_dtype_dcn=None),
                             mesh, dcn_inner=4)
    np.testing.assert_array_equal(np.asarray(hier_none.out),
                                  np.asarray(hier.out))


@pytest.mark.slow
def test_dcn_wire_fp8_hop_close_to_oracle_with_per_hop_error(devices):
    """An fp8 DCN hop under a raw ICI hop: output stays close to the
    oracle (one fp8 round trip per leg), and MoEStats reports the two
    hops' round-trip errors separately — ici proxy 0 (leg wire off),
    dcn proxy > 0."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=8, collect_stats=True,
                    wire_dtype_dcn="e4m3", **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    out = ep_moe_layer(params, x, cfg, mesh, dcn_inner=4)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(np.asarray(out.out), np.asarray(want),
                               atol=0.25)
    assert float(out.stats.wire_rtq_error) == 0.0
    assert 0.0 < float(out.stats.wire_rtq_error_dcn) < 0.1
    # both wires on: both proxies populated, independently
    both = cfg.replace(wire_dtype="bf16")
    ob = ep_moe_layer(params, x, both, mesh, dcn_inner=4)
    assert float(ob.stats.wire_rtq_error) > 0.0
    assert float(ob.stats.wire_rtq_error_dcn) > 0.0


def test_dcn_wire_split_hops_through_chunked_pipeline(devices):
    """The per-hop codec composes with the chunked double-buffered
    pipeline: every chunk re-encodes its DCN hop, output stays close
    to the serial split-wire result."""
    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, ep=8,
                    wire_dtype_dcn="e4m3", **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    serial = ep_moe_layer(params, x, cfg, mesh, dcn_inner=4)
    chunked = ep_moe_layer(params, x, cfg.replace(a2a_chunks=2),
                           mesh, dcn_inner=4)
    np.testing.assert_allclose(np.asarray(chunked.out),
                               np.asarray(serial.out),
                               rtol=1e-6, atol=1e-6)


def test_dcn_wire_rejected_with_fused_backend():
    with pytest.raises(ValueError, match="fused"):
        MoEConfig(num_experts=8, ep=8, moe_backend="fused",
                  wire_dtype_dcn="e4m3")


def test_transport_cost_prices_dcn_hop_at_its_own_wire():
    """a2a_transport_cost(dcn_slab_bytes=): the hierarchical DCN term
    serializes at the dcn-wire slab while flat (no re-encode hop) and
    the ICI stage stay at the leg slab — the modeled reason
    fp8-across-DCN + aggregation beats flat-uncompressed."""
    from flashmoe_tpu.analysis import a2a_transport_cost

    raw, fp8 = 256 * 1024, 66 * 1024
    base = a2a_transport_cost(8, 2, raw, gen="v5e")
    comp = a2a_transport_cost(8, 2, raw, gen="v5e",
                              dcn_slab_bytes=fp8)
    assert comp["hierarchical"]["dcn_ms"] < base["hierarchical"]["dcn_ms"]
    assert comp["hierarchical"]["ici_ms"] == base["hierarchical"]["ici_ms"]
    assert comp["flat"] == base["flat"]


def test_wire_row_bytes_per_hop():
    from flashmoe_tpu.analysis import wire_row_bytes

    cfg = MoEConfig(num_experts=8, hidden_size=128,
                    wire_dtype_dcn="e4m3", **F32)
    assert wire_row_bytes(cfg, "dispatch", "ici") == 128 * 4
    assert wire_row_bytes(cfg, "dispatch", "dcn") == 128 * 1 + 4
    # inherit: no override -> both hops price identically
    off = cfg.replace(wire_dtype_dcn=None, wire_dtype="bf16")
    assert wire_row_bytes(off, "dispatch", "dcn") \
        == wire_row_bytes(off, "dispatch", "ici") == 128 * 2
    with pytest.raises(ValueError, match="hop"):
        wire_row_bytes(cfg, "dispatch", "sideways")


# ----------------------------------------------------------------------
# Decider-driven DP x EP group formation at bootstrap (ISSUE 13)
# ----------------------------------------------------------------------

def test_mock_slices_feed_dcn_edges_into_adjacency(monkeypatch, devices):
    """device_slice_ids honors the mock, and ici_adjacency prices
    cross-block pairs at DCN cost — the Decider sees a genuinely
    heterogeneous fabric on the virtual mesh."""
    from flashmoe_tpu.parallel.topology import (
        device_slice_ids, ici_adjacency,
    )

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    assert device_slice_ids(devices[:8]) == [0] * 4 + [1] * 4
    adj = ici_adjacency(devices[:8], platform="v5e")
    # cross-slice = DCN (10us, 25GB/s); in-slice = v5e ICI (1us, 45GB/s)
    assert adj.alpha[0, 7] > adj.alpha[0, 1]
    assert adj.beta[0, 7] > adj.beta[0, 1]


def test_form_groups_ep_across_dcn_on_mocked_mesh(monkeypatch, devices):
    """On a cheap-DCN mock the Decider merges across slices: one EP
    group spanning both, classified ep_across_dcn with the two-stage
    blocking published."""
    from flashmoe_tpu.runtime.bootstrap import form_groups

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    cfg = MoEConfig(num_experts=8, hidden_size=128,
                    intermediate_size=256, sequence_len=128, **F32)
    plan = form_groups(cfg, devices[:8])
    assert plan.mapping == "ep_across_dcn"
    assert (plan.dp, plan.ep) == (1, 8)
    assert plan.dcn_inner == 4
    assert plan.slices == (2, 4)


def test_form_groups_dp_across_dcn_when_dcn_expensive(monkeypatch,
                                                      devices):
    """With the DCN edges priced prohibitively (and per-slice memory
    sufficient), the Decider keeps one EP group per slice — DP crosses
    DCN, the a2a never leaves ICI, and the Runtime adopts the
    factorization (ep folded to the group size)."""
    from flashmoe_tpu.parallel.topology import (
        ici_adjacency, measured_worker_attrs,
    )
    from flashmoe_tpu.runtime import bootstrap

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    monkeypatch.setenv("FLASHMOE_MEMORY_GB", "64")
    cfg = MoEConfig(num_experts=8, hidden_size=128,
                    intermediate_size=256, sequence_len=128, **F32)
    adj = ici_adjacency(devices[:8], platform="v5e")
    sids = [0] * 4 + [1] * 4
    for i in range(8):
        for j in range(8):
            if sids[i] != sids[j]:
                adj.alpha[i, j] *= 1e4
                adj.beta[i, j] *= 1e4
    workers = measured_worker_attrs(devices[:8], cfg, probe=False)
    plan = bootstrap.form_groups(cfg, devices[:8], adj=adj,
                                 workers=workers)
    assert plan.mapping == "dp_across_dcn"
    assert (plan.dp, plan.ep) == (2, 4)
    assert plan.dcn_inner is None
    assert plan.groups == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_initialize_records_groups_and_respects_pinned_ep(monkeypatch,
                                                          devices):
    """The bootstrap records a bootstrap.groups decision; an explicit
    user ep is never overridden by the Decider's factorization."""
    from flashmoe_tpu.runtime import bootstrap
    from flashmoe_tpu.utils.telemetry import metrics

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    monkeypatch.setattr(bootstrap, "_runtime", None)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=128, ep=8,
                    **F32)
    rt = bootstrap.initialize(cfg, measure=False)
    try:
        assert rt.cfg.ep == 8              # pinned ep stands
        assert rt.group_plan is not None
        rec = metrics.last_decision("bootstrap.groups")
        assert rec is not None
        assert rec["ep_pinned"] is True
        assert rec["slices"] == [2, 4]
    finally:
        monkeypatch.setattr(bootstrap, "_runtime", None)


def test_assign_experts_sliced_colocates_hot_pairs():
    """The slice-aware cost-sorted multiset: the two hottest experts
    (a top-2 routing companion pair) land in the SAME slice, the
    slices stay load-balanced, and the assignment is deterministic."""
    from flashmoe_tpu.parallel.decider import assign_experts_sliced

    group = list(range(8))
    rates = [1.0] * 8
    slice_of = [0] * 4 + [1] * 4
    costs = [100.0, 90.0, 10.0, 9.0, 8.0, 7.0, 6.0, 5.0]
    out = assign_experts_sliced(group, rates, 8, slice_of, costs)
    slice_of_expert = {e: slice_of[d] for d, es in out.items()
                      for e in es}
    # the hot pair co-locates; every expert assigned exactly once
    assert slice_of_expert[0] == slice_of_expert[1]
    assert sorted(e for es in out.values() for e in es) == list(range(8))
    # load balance: the other slice carries the cold tail, not nothing
    loads = {0: 0.0, 1: 0.0}
    for e, s in slice_of_expert.items():
        loads[s] += costs[e]
    assert min(loads.values()) > 0
    out2 = assign_experts_sliced(group, rates, 8, slice_of, costs)
    assert out == out2


def test_decide_routes_sliced_assignment(monkeypatch, devices):
    """decide(slice_of=, expert_costs=) on a group spanning slices
    uses the slice-aware assignment (hot pair in one slice)."""
    from flashmoe_tpu.parallel.decider import decide
    from flashmoe_tpu.parallel.topology import (
        WorkerAttr, ici_adjacency,
    )

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=128, **F32)
    adj = ici_adjacency(devices[:8], platform="v5e")
    workers = [WorkerAttr(throughput=1.0, memory_gb=64.0)] * 8
    costs = [100.0, 90.0, 10.0, 9.0, 8.0, 7.0, 6.0, 5.0]
    slice_of = [0] * 4 + [1] * 4
    p = decide(adj, workers, cfg, expert_costs=costs,
               slice_of=slice_of)
    owner = {e: d for d, es in p.local_experts.items() for e in es
             if d in p.groups[0]}
    assert slice_of[owner[0]] == slice_of[owner[1]]
