"""Two-stage ICI+DCN transport (VERDICT r4 missing #2 / next #5).

The reference resolves P2P vs remote per peer at init
(``bootstrap.cuh:442-446``) and branches transport at every send
(``os/packet.cuh:221-258``).  The TPU equivalent: when the ep axis spans
slices, the collective path's all-to-all decomposes into an intra-slice
ICI exchange + ONE aggregated DCN message per slice pair
(``parallel/ep.py:_hierarchical_a2a``), selected automatically from the
detected slice blocking (``topology.slice_structure``) the way the
arrival-order schedule is published.  The virtual 8-device CPU mesh
mocks a 2x4 "two-slice" job via ``FLASHMOE_MOCK_SLICES``."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.topology import slice_structure

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _setup(cfg, seed=0):
    pk, xk = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(pk, cfg)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), jnp.float32)
    return params, x


def test_hierarchical_a2a_matches_flat_and_oracle(devices):
    """The two-stage exchange is a pure re-decomposition: bit-identical
    routing to the flat all-to-all, oracle-correct output, both
    directions (dispatch and combine-return)."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    flat = ep_moe_layer(params, x, cfg, mesh, dcn_inner=0)
    hier = ep_moe_layer(params, x, cfg, mesh, dcn_inner=4)
    np.testing.assert_allclose(np.asarray(hier.out), np.asarray(flat.out),
                               rtol=1e-6, atol=1e-6)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(np.asarray(hier.out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("inner", [2, 4])
def test_hierarchical_a2a_other_factorizations(inner, devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    capacity_factor=1.0, drop_tokens=True, ep=8, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    flat = ep_moe_layer(params, x, cfg, mesh, dcn_inner=0)
    hier = ep_moe_layer(params, x, cfg, mesh, dcn_inner=inner)
    np.testing.assert_allclose(np.asarray(hier.out), np.asarray(flat.out),
                               rtol=1e-6, atol=1e-6)


def test_slice_structure_detection(monkeypatch, devices):
    """Mocked two-slice blocking is detected; single-slice returns None;
    irregular mocks fall back to None (flat transport stands)."""
    monkeypatch.delenv("FLASHMOE_MOCK_SLICES", raising=False)
    assert slice_structure(devices[:8]) is None  # CPU: one process
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    assert slice_structure(devices[:8]) == (2, 4)
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "3")
    assert slice_structure(devices[:8]) is None  # 8 % 3 != 0
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "8")
    assert slice_structure(devices[:8]) == (8, 1)


def test_bootstrap_publishes_dcn_inner(monkeypatch, devices):
    """An initialized runtime on a mocked 2-slice job publishes
    ranks-per-slice, ep_moe_layer picks it up by default (same pattern
    as the arrival-order table), and the gated accessor refuses meshes
    whose device order differs from jax.devices()."""
    from flashmoe_tpu.runtime import bootstrap

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    monkeypatch.setattr(bootstrap, "_runtime", None)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256,
                    drop_tokens=False, ep=8, **F32)
    rt = bootstrap.initialize(cfg, use_decider=False, measure=False)
    try:
        assert rt.dcn_inner == 4
        mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:8])
        assert bootstrap.current_dcn_inner(mesh, 8) == 4
        # permuted mesh: the blocking indexes jax.devices() order
        perm = list(jax.devices()[:8])
        perm[0], perm[1] = perm[1], perm[0]
        mesh_p = make_mesh(cfg, dp=1, devices=perm)
        assert bootstrap.current_dcn_inner(mesh_p, 8) is None
        # end to end: the default path must produce oracle output while
        # riding the published two-stage exchange
        params, x = _setup(cfg)
        out = ep_moe_layer(params, x, cfg, mesh)
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(np.asarray(out.out), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
    finally:
        monkeypatch.setattr(bootstrap, "_runtime", None)


def test_transport_cost_model_prefers_aggregation():
    """The modeled reason the two-stage exchange exists: identical
    cross-slice bytes, inner-times fewer DCN messages — so at MoE slab
    sizes (sub-MB per peer) the alpha savings dominate the extra
    in-slice hop and the hierarchical total wins."""
    from flashmoe_tpu.analysis import a2a_transport_cost

    c = a2a_transport_cost(8, 4, slab_bytes=256 * 1024, gen="v5e")
    assert c["hierarchical"]["dcn_messages"] * 4 == c["flat"]["dcn_messages"]
    assert c["hierarchical"]["total_ms"] < c["flat"]["total_ms"]
    # same bytes must cross DCN either way (aggregation, not elision):
    # beta terms equal once the alpha terms are stripped
    strip = lambda leg, n_msg: leg["dcn_ms"] - n_msg * (10.0 / 1e3)
    np.testing.assert_allclose(
        strip(c["flat"], c["flat"]["dcn_messages"]),
        strip(c["hierarchical"], c["hierarchical"]["dcn_messages"]),
        rtol=1e-9,
    )
    # at very large slabs the extra in-slice traffic can flip the total:
    # the model must expose that crossover rather than hide it
    big = a2a_transport_cost(8, 4, slab_bytes=64 * 2**20, gen="v5e")
    assert big["hierarchical"]["ici_ms"] > big["flat"]["ici_ms"]
