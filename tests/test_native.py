"""Native (C++) decider: builds, loads, and agrees with the Python one."""

import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel import _native
from flashmoe_tpu.parallel.decider import decide
from flashmoe_tpu.parallel.topology import Adjacency, WorkerAttr


def _island_adj(n=8, cut=4, slow_alpha=0.5, slow_beta=0.05):
    alpha = np.full((n, n), 0.01)
    beta = np.full((n, n), 0.001)
    for i in range(n):
        for j in range(n):
            if (i < cut) != (j < cut):
                alpha[i, j] = slow_alpha
                beta[i, j] = slow_beta
        alpha[i, i] = beta[i, i] = 0
    return Adjacency(alpha, beta)


@pytest.fixture(scope="module")
def native_lib():
    lib = _native.load()
    if lib is None:
        pytest.skip("g++ unavailable; native decider not built")
    return lib


def test_builds_and_loads(native_lib):
    assert native_lib.flashmoe_native_abi_version() == 1


@pytest.mark.parametrize("scenario", ["uniform", "islands", "hetero"])
def test_native_matches_python(native_lib, scenario):
    n = 8
    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=1024,
                    intermediate_size=1024, sequence_len=8192,
                    mini_batch=4 if scenario == "islands" else 1)
    if scenario == "islands":
        adj = _island_adj(slow_alpha=1000.0, slow_beta=100.0)
        cfg = cfg.replace(hidden_size=4096)
    else:
        adj = _island_adj()
    if scenario == "hetero":
        workers = [WorkerAttr(throughput=3.0 if d < 2 else 1.0,
                              memory_gb=16.0) for d in range(n)]
    else:
        workers = [WorkerAttr(throughput=1.0, memory_gb=16.0)
                   for _ in range(n)]

    py = decide(adj, workers, cfg, native=False)
    cc = decide(adj, workers, cfg, native=True)
    assert py.groups == cc.groups, (py.groups, cc.groups)
    assert py.local_experts == cc.local_experts


@pytest.mark.parametrize("training", [True, False], ids=["train", "infer"])
def test_native_matches_python_gateway(native_lib, training):
    """The bottleneck-edge PQ pricing and the inference specialization
    (round-3 additions) must agree between C++ and Python on the
    DCN-gateway topology where they change the grouping decision."""
    n = 4
    alpha = np.zeros((n, n))
    beta = np.zeros((n, n))
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            if (i < 2) == (j < 2):
                beta[i, j] = 0.05 if i < 2 else 0.001
            else:
                alpha[i, j] = 10.0
                beta[i, j] = 0.002
    adj = Adjacency(alpha, beta)
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=128,
                    vocab_size=8192, num_layers=1, is_training=training)
    workers = [WorkerAttr(throughput=1.0, memory_gb=16.0)
               for _ in range(n)]
    py = decide(adj, workers, cfg, native=False)
    cc = decide(adj, workers, cfg, native=True)
    assert py.groups == cc.groups, (py.groups, cc.groups)
    assert len(py.groups) == (1 if training else 2)


def test_native_memory_forcing(native_lib):
    cfg = MoEConfig(num_experts=64, expert_top_k=2, hidden_size=4096,
                    intermediate_size=4096)
    workers = [WorkerAttr(throughput=1.0, memory_gb=2.0) for _ in range(8)]
    adj = _island_adj(slow_alpha=1000.0, slow_beta=100.0)
    py = decide(adj, workers, cfg, native=False)
    cc = decide(adj, workers, cfg, native=True)
    assert py.groups == cc.groups
