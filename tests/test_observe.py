"""Observability subsystem: in-graph MoE stats, flight recorder,
Prometheus exposition, planner drift monitor, and the observe CLI."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.ops.stats import MoEStats, moe_stats
from flashmoe_tpu.utils.telemetry import (
    FlightRecorder, Histogram, Metrics, metrics as global_metrics,
)

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


# ----------------------------------------------------------------------
# In-graph stats: known routing -> exact histogram / drop fraction
# ----------------------------------------------------------------------

def _routed_setup():
    """Deterministic routing: gate_w reads the expert id off the one-hot
    token, so expert loads are exactly the planted choice vector."""
    cfg = MoEConfig(num_experts=4, expert_top_k=1, hidden_size=64,
                    intermediate_size=64, sequence_len=16,
                    capacity_factor=1.0, collect_stats=True, **F32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    gw = np.zeros((64, 4), np.float32)
    for e in range(4):
        gw[e, e] = 10.0
    params["gate_w"] = jnp.asarray(gw)
    # 10 tokens to expert 0, 2 each to 1/2/3; capacity_for(16) = 8
    choice = [0] * 10 + [1, 1, 2, 2, 3, 3]
    x = np.zeros((16, 64), np.float32)
    for i, c in enumerate(choice):
        x[i, c] = 1.0
    return cfg, params, jnp.asarray(x)


def _check_exact(st):
    np.testing.assert_array_equal(np.asarray(st.expert_load),
                                  [10.0, 2.0, 2.0, 2.0])
    # capacity 8: expert 0 drops 2 of 10 -> 2/16 dropped, 14/32 slots used
    assert float(st.dropped_fraction) == pytest.approx(2 / 16)
    assert float(st.capacity_utilization) == pytest.approx(14 / 32)
    assert float(st.imbalance) == pytest.approx(10 / 4)
    assert float(st.topk_confidence) == pytest.approx(1.0)
    assert float(st.router_entropy) > 0


def test_stats_known_routing_exact():
    cfg, params, x = _routed_setup()
    assert cfg.capacity_for(16) == 8
    _check_exact(moe_layer(params, x, cfg, use_pallas=False).stats)


def test_stats_under_jit():
    cfg, params, x = _routed_setup()
    st = jax.jit(
        lambda xx: moe_layer(params, xx, cfg, use_pallas=False).stats
    )(x)
    _check_exact(st)


def test_stats_under_vmap():
    cfg, params, x = _routed_setup()
    st = jax.vmap(
        lambda xx: moe_layer(params, xx, cfg, use_pallas=False).stats
    )(jnp.stack([x, x, x]))
    assert st.expert_load.shape == (3, 4)
    np.testing.assert_array_equal(np.asarray(st.expert_load[1]),
                                  [10.0, 2.0, 2.0, 2.0])
    np.testing.assert_allclose(np.asarray(st.dropped_fraction),
                               [2 / 16] * 3, rtol=1e-6)


def test_stats_dropless_reports_no_drops():
    cfg, params, x = _routed_setup()
    r_like = moe_layer(params, x, cfg, use_pallas=False)
    st = moe_stats(
        type("R", (), {
            "expert_counts": r_like.stats.expert_load,
            "combine_weights": jnp.ones((16, 1), jnp.float32),
            "probs_mean": jnp.zeros((4,), jnp.float32),
        })(), cfg, None)
    assert float(st.dropped_fraction) == 0.0
    assert float(st.capacity_utilization) == 1.0


def test_stats_off_by_default():
    cfg, params, x = _routed_setup()
    o = moe_layer(params, x, cfg.replace(collect_stats=False),
                  use_pallas=False)
    assert o.stats is None


# ----------------------------------------------------------------------
# EP layer: flag off is bit-identical with no extra collectives
# ----------------------------------------------------------------------

def _prim_counts(jaxpr, acc=None):
    acc = {} if acc is None else acc
    for eqn in jaxpr.eqns:
        acc[eqn.primitive.name] = acc.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            vs = v if isinstance(v, (list, tuple)) else (v,)
            for item in vs:
                if hasattr(item, "jaxpr"):
                    _prim_counts(item.jaxpr, acc)
                elif hasattr(item, "eqns"):
                    _prim_counts(item, acc)
    return acc


COLLECTIVES = ("all_to_all", "psum", "pmean", "all_gather", "ppermute",
               "ragged_all_to_all")


@pytest.mark.slow
def test_ep_stats_off_bit_identical_no_extra_collectives(devices):
    from flashmoe_tpu.parallel.ep import ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=8, **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 64),
                          jnp.float32)

    def collectives(c):
        jx = jax.make_jaxpr(
            lambda p, xx: ep_moe_layer(p, xx, c, mesh))(params, x)
        pc = _prim_counts(jx.jaxpr)
        return {k: v for k, v in pc.items() if k in COLLECTIVES}

    off = collectives(cfg)
    # the stats-off graph is exactly the pre-observability graph: the
    # two slab exchanges plus the three aux/z/counts reductions
    assert off == {"all_to_all": 2, "psum": 3}
    on = collectives(cfg.replace(collect_stats=True))
    assert on["all_to_all"] == 2  # stats never add an exchange

    o_off = ep_moe_layer(params, x, cfg, mesh)
    o_on = ep_moe_layer(params, x, cfg.replace(collect_stats=True), mesh)
    assert o_off.stats is None
    np.testing.assert_array_equal(np.asarray(o_off.out),
                                  np.asarray(o_on.out))
    # global stats line up with the psum'd counts the layer already emits
    np.testing.assert_array_equal(np.asarray(o_on.stats.expert_load),
                                  np.asarray(o_on.expert_counts,
                                             dtype=np.float32))
    assert float(o_on.stats.expert_load.sum()) == cfg.tokens * 2


# ----------------------------------------------------------------------
# Flight recorder + histogram + Prometheus exposition
# ----------------------------------------------------------------------

def test_flight_recorder_ring_bounds(tmp_path):
    fr = FlightRecorder(capacity=16)
    for i in range(100):
        fr.record(step=i, loss=float(i))
    assert len(fr) == 16 and fr.capacity == 16
    assert fr.records[0]["step"] == 84
    assert fr.records[-1]["step"] == 99
    path = str(tmp_path / "flight.jsonl")
    assert fr.export_jsonl(path) == 16
    lines = [json.loads(l) for l in open(path)]
    assert [l["step"] for l in lines] == list(range(84, 100))


def test_histogram_percentiles():
    h = Histogram(buckets=(1.0, 2.0, 5.0, 10.0))
    for v in (0.5, 1.5, 1.6, 4.0, 9.0, 20.0):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 6
    assert s["sum"] == pytest.approx(36.6)
    assert s["min"] == 0.5 and s["max"] == 20.0
    assert s["p50"] <= s["p99"] <= 20.0


def test_prometheus_exposition_format():
    import re

    m = Metrics()
    m.count("steps")
    m.count("steps")
    m.gauge("lr", 3e-4)
    m.histogram("step_ms", 3.0, buckets=(1.0, 5.0, 10.0))
    m.histogram("step_ms", 7.0, buckets=(1.0, 5.0, 10.0))
    with m.timer("fwd"):
        pass
    text = m.prometheus_text()
    assert "# TYPE flashmoe_steps_total counter" in text
    assert "flashmoe_steps_total 2.0" in text
    assert "# TYPE flashmoe_lr gauge" in text
    assert "# TYPE flashmoe_step_ms histogram" in text
    assert 'flashmoe_step_ms_bucket{le="5"} 1' in text
    assert 'flashmoe_step_ms_bucket{le="+Inf"} 2' in text
    assert "flashmoe_step_ms_count 2" in text
    assert "# TYPE flashmoe_fwd_seconds summary" in text
    # every sample line obeys the exposition grammar
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+-]+$")
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            assert sample.match(line), line


def test_metric_name_sanitized():
    m = Metrics()
    m.count("planner.drift/err-rate")
    text = m.prometheus_text()
    assert "flashmoe_planner_drift_err_rate_total" in text


# ----------------------------------------------------------------------
# Drift monitor
# ----------------------------------------------------------------------

def test_drift_monitor_thresholding():
    from flashmoe_tpu.planner.drift import record_drift

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256, **F32)
    n0 = len(global_metrics.decisions)
    # within threshold: no warning, decision recorded
    rec = record_drift(cfg, "explicit", measured_ms=1.2, gen="v5e",
                       predicted_ms=1.0, threshold=0.5)
    assert not rec.exceeded
    assert rec.rel_error == pytest.approx(0.2)
    with pytest.warns(RuntimeWarning, match="planner drift"):
        rec = record_drift(cfg, "explicit", measured_ms=2.0, gen="v5e",
                           predicted_ms=1.0, threshold=0.5)
    assert rec.exceeded
    new = global_metrics.decisions[n0:]
    assert [d["decision"] for d in new] == ["planner.drift"] * 2
    assert new[-1]["exceeded"] is True
    assert new[-1]["measured_ms"] == 2.0


def test_drift_predicts_when_not_given():
    from flashmoe_tpu.planner.drift import record_drift

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256, **F32)
    rec = record_drift(cfg, "explicit", measured_ms=1e9, gen="v5e",
                       warn=False)
    assert rec.predicted_ms > 0
    assert rec.exceeded  # a second per layer is drift by any threshold


def test_drift_report_over_mixed_records():
    from flashmoe_tpu.planner.drift import drift_report

    records = [
        {"decision": "planner.drift", "path": "explicit", "gen": "v5e",
         "rel_error": 0.4, "exceeded": False},
        {"decision": "planner.drift", "path": "explicit", "gen": "v5e",
         "rel_error": -0.8, "exceeded": True},
        # a bench record doubles as a calibration point
        {"metric": "moe_layer_fwd_ms[x]", "value": 2.0, "path": "explicit",
         "predicted_ms": 1.0, "prediction_error": 1.0,
         "planner_gen": "v5e", "drift_exceeded": True},
        {"unrelated": True},
    ]
    rep = drift_report(records)
    assert rep["n"] == 3 and rep["exceeded"] == 2
    b = rep["by_path"]["explicit@v5e"]
    assert b["n"] == 3
    assert b["worst_rel_error"] == pytest.approx(1.0)


def test_drift_report_dedups_mirrored_bench_pair():
    """bench.py writes each measurement twice across the obs-dir pair
    (bench record + mirrored planner.drift decision): one comparison."""
    from flashmoe_tpu.planner.drift import drift_report

    # measured value where bench's 3-decimal and the decision's
    # 4-decimal rounding differ — the dedup must still match
    bench_rec = {"metric": "moe_layer_fwd_ms[x]", "value": 1.235,
                 "path": "explicit", "predicted_ms": 0.015,
                 "prediction_error": 81.3, "planner_gen": "v5e",
                 "d": 1, "drift_exceeded": True}
    decision = {"decision": "planner.drift", "path": "explicit",
                "gen": "v5e", "d": 1, "predicted_ms": 0.015,
                "measured_ms": 1.2346, "rel_error": 81.3067,
                "exceeded": True}
    rep = drift_report([bench_rec, decision])
    assert rep["n"] == 1 and rep["exceeded"] == 1
    assert rep["by_path"]["explicit@v5e"]["n"] == 1


# ----------------------------------------------------------------------
# Observe CLI
# ----------------------------------------------------------------------

def _synthetic_flight(tmp_path):
    """Two steps of a hand-computed routing case: E=4, 16 assignments
    per step, loads [10, 2, 2, 2] at capacity 8 -> dropped 2/16."""
    path = str(tmp_path / "flight.jsonl")
    with open(path, "w") as f:
        for step in range(2):
            f.write(json.dumps({
                "step": step, "loss": 3.0 - step, "step_ms": 12.5,
                "moe": [{
                    "layer": 0, "expert_load": [10.0, 2.0, 2.0, 2.0],
                    "dropped_fraction": 0.125,
                    "capacity_utilization": 14 / 32,
                    "imbalance": 2.5, "router_entropy": 1.0,
                    "topk_confidence": 1.0,
                }],
            }) + "\n")
    return path


def test_observe_cli_summarizes_synthetic_dump(tmp_path, capsys):
    from flashmoe_tpu import observe

    path = _synthetic_flight(tmp_path)
    assert observe.main([path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["flight_steps"] == 2
    # nonzero expert-load histogram, summed over steps
    assert doc["imbalance"]["expert_load"] == [20.0, 4.0, 4.0, 4.0]
    assert doc["imbalance"]["imbalance"] == pytest.approx(2.5)
    # drop-rate figure matches the hand-computed routing case
    assert doc["drops"]["mean_dropped_fraction"] == pytest.approx(0.125)
    assert doc["drops"]["timeline"][0]["dropped_fraction"] == \
        pytest.approx(0.125)
    assert doc["phases"]["step_ms"] == pytest.approx(12.5)


def test_observe_cli_text_output(tmp_path, capsys):
    from flashmoe_tpu import observe

    path = _synthetic_flight(tmp_path)
    assert observe.main([path]) == 0
    out = capsys.readouterr().out
    assert "expert load histogram" in out
    assert "drop rate: mean 0.125" in out


def test_observe_wire_report(tmp_path, capsys):
    """Flight records carrying the wire round-trip error surface in the
    wire report (and the text rendering); wire-off dumps report none."""
    from flashmoe_tpu import observe

    path = str(tmp_path / "flight.jsonl")
    with open(path, "w") as f:
        for step, err in enumerate([0.0, 0.021, 0.025]):
            f.write(json.dumps({
                "step": step,
                "moe": [{"expert_load": [1.0], "wire_rtq_error": err}],
            }) + "\n")
    assert observe.main([path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["wire"]["steps_with_wire"] == 2  # the 0.0 step = wire off
    assert doc["wire"]["max_rtq_error"] == pytest.approx(0.025)
    assert doc["wire"]["mean_rtq_error"] == pytest.approx(0.023)
    assert observe.main([path]) == 0
    assert "wire compression" in capsys.readouterr().out
    # a wire-off dump carries no wire section in the text rendering
    off = _synthetic_flight(tmp_path)
    assert observe.main([off]) == 0
    assert "wire compression" not in capsys.readouterr().out


def test_observe_cli_rejects_empty(tmp_path, capsys):
    from flashmoe_tpu import observe

    p = str(tmp_path / "empty.jsonl")
    open(p, "w").close()
    assert observe.main([p]) == 2


def test_observe_resilience_report(tmp_path, capsys):
    """The decision stream of a preempted-then-resumed run summarizes
    into the resilience narrative (docs/RESILIENCE.md decisions)."""
    from flashmoe_tpu import observe

    decisions = [
        {"decision": "preempt.notice", "source": "SIGTERM",
         "grace_s": 30.0},
        {"decision": "preempt.drain", "step": 4, "source": "SIGTERM",
         "remaining_grace_s": 28.5},
        {"decision": "supervisor.resume", "incarnation": 1, "step": 4,
         "world": 4, "ep": 2, "dp": 2},
        {"decision": "supervisor.resume", "incarnation": 2, "step": 6,
         "world": 2, "ep": 2, "dp": 1},
        {"decision": "trainer.grad_skip", "step": 5, "grad_norm": 1e9},
        {"decision": "checkpoint.fallback", "corrupt_step": 8,
         "restored_step": 6, "lost_steps": 2},
    ]
    p = str(tmp_path / "decisions.jsonl")
    with open(p, "w") as f:
        for d in decisions:
            f.write(json.dumps(d) + "\n")
    assert observe.main([p, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    res = doc["resilience"]
    assert res["events"] == {"trainer.grad_skip": 1,
                             "checkpoint.fallback": 1,
                             "preempt.notice": 1, "preempt.drain": 1,
                             "supervisor.resume": 2}
    assert res["drains"] == [{"step": 4, "source": "SIGTERM",
                              "remaining_grace_s": 28.5}]
    assert res["worlds"] == [2, 4]  # the elastic re-fold is visible
    assert res["resumes"][1]["ep"] == 2 and res["resumes"][1]["dp"] == 1

    assert observe.main([p]) == 0
    out = capsys.readouterr().out
    assert "resilience events:" in out
    assert "drain at step 4 (SIGTERM), 28.5s grace left" in out
    assert "resume #2 at step 6: world=2 (ep=2 x dp=1)" in out


# ----------------------------------------------------------------------
# End to end: trainer flight recorder -> observe summary
# ----------------------------------------------------------------------

def test_trainer_flight_recorder_end_to_end(tmp_path, devices):
    from flashmoe_tpu import observe
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.runtime.trainer import train

    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=32, num_layers=1,
                    moe_frequency=1, vocab_size=512, num_heads=2,
                    capacity_factor=1.0, is_training=True, ep=4,
                    collect_stats=True, **F32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])

    def batches():
        k = jax.random.PRNGKey(0)
        while True:
            k, sk = jax.random.split(k)
            yield {"tokens": jax.random.randint(sk, (1, 33), 0, 512)}

    fp = str(tmp_path / "flight.jsonl")
    _, hist = train(cfg, mesh, batches(), num_steps=1, log_every=1,
                    flight_path=fp)
    assert "moe" in hist[-1] and hist[-1]["moe"][0]["expert_load"]

    records = observe.load_jsonl([fp])
    assert len(records) == 1
    doc = observe.summarize(records)
    assert doc["flight_steps"] == 1
    # one step routes 32 tokens x top-2 = 64 assignments
    assert doc["imbalance"]["total_assignments"] == pytest.approx(64.0)
    assert sum(doc["imbalance"]["expert_load"]) > 0
    assert doc["drops"]["mean_dropped_fraction"] is not None


# ----------------------------------------------------------------------
# bench.py wiring: drift decisions land in telemetry
# ----------------------------------------------------------------------

def test_bench_emit_records_drift(monkeypatch, capsys):
    import bench

    monkeypatch.setenv("FLASHMOE_TPU_GEN", "v5e")
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=256, **F32)
    n0 = len(global_metrics.decisions)
    bench._PARTIAL.clear()
    bench._emit(cfg, "unit", t_fused=5e-3, t_xla=8e-3)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["predicted_ms"] > 0
    assert "drift_exceeded" in rec
    drifts = [d for d in global_metrics.decisions[n0:]
              if d["decision"] == "planner.drift"]
    # executed path + the xla comparison leg
    assert {d["path"] for d in drifts} == {rec["path"], "xla"}


def test_adaptation_report_timeline_with_before_after():
    from flashmoe_tpu.observe import adaptation_report

    flight = [
        {"step": s,
         "moe": [{"layer": 0, "imbalance": 4.0 if s < 5 else 1.2,
                  "dropped_fraction": 0.3 if s < 5 else 0.0}]}
        for s in range(10)
    ]
    records = flight + [
        {"decision": "controller.morph", "step": 5, "trigger": "skew",
         "backend": "local", "dropless": True,
         "overrides": {"drop_tokens": False}, "reason": "drills"},
        {"decision": "controller.cooldown", "step": 7,
         "trigger": "skew", "until": 9},
        {"decision": "controller.demotion_reset", "incarnation": 1,
         "world": 2, "dropped": ["fused"]},
    ]
    rep = adaptation_report(records)
    assert rep["actions"] == {"controller.morph": 1,
                              "controller.cooldown": 1,
                              "controller.demotion_reset": 1}
    morph = next(t for t in rep["timeline"]
                 if t["decision"] == "controller.morph")
    assert morph["before"]["imbalance"] > morph["after"]["imbalance"]
    assert morph["before"]["dropped_fraction"] > \
        morph["after"]["dropped_fraction"]
    # the summary document carries the section
    from flashmoe_tpu.observe import render_text, summarize

    text = render_text(summarize(records))
    assert "self-healing controller" in text
    assert "morph" in text


def test_adaptation_report_empty_without_controller_decisions():
    from flashmoe_tpu.observe import adaptation_report

    rep = adaptation_report([{"decision": "planner.drift"}])
    assert rep == {"actions": {}, "timeline": []}
