"""GPipe pipeline parallelism over the pp mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.transformer import init_params, loss_fn
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.pipeline import pipeline_loss, stack_stage_params

CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=32, num_layers=4,
                moe_frequency=1, vocab_size=256, num_heads=2,
                drop_tokens=False, dtype=jnp.float32,
                param_dtype=jnp.float32, pp=4, dp=2)


def _batch(b=4, seed=1):
    return {"tokens": jax.random.randint(
        jax.random.PRNGKey(seed), (b, CFG.sequence_len + 1), 0,
        CFG.vocab_size)}


@pytest.mark.parametrize("pp,dp,mb", [(4, 2, 2), (2, 4, 4), (2, 2, 1)])
@pytest.mark.slow
def test_pipeline_ce_matches_plain_forward(pp, dp, mb, devices):
    cfg = CFG.replace(pp=pp, dp=dp)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = _batch(b=dp * mb)  # per-dp-rank batch == microbatch count
    mesh = make_mesh(cfg, devices=devices[:pp * dp])
    total, m = pipeline_loss(params, batch, cfg, mesh, num_microbatches=mb)
    _, wm = loss_fn(params, batch, cfg, None)
    np.testing.assert_allclose(float(m["ce"]), float(wm["ce"]), rtol=1e-5)


@pytest.mark.parametrize("mb", [2, 4])
@pytest.mark.slow
def test_interleaved_schedule_matches_gpipe(mb, devices):
    """interleave=2 (Megatron-style two chunks per stage) computes the
    same loss as GPipe — identical math, fewer bubble ticks — and matches
    the plain forward."""
    cfg = CFG.replace(pp=2, dp=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = _batch(b=2 * mb)
    mesh = make_mesh(cfg, devices=devices[:4])
    t_i, m_i = pipeline_loss(params, batch, cfg, mesh,
                             num_microbatches=mb, interleave=2)
    t_g, m_g = pipeline_loss(params, batch, cfg, mesh,
                             num_microbatches=mb, interleave=1)
    np.testing.assert_allclose(float(m_i["ce"]), float(m_g["ce"]),
                               rtol=1e-5)
    _, wm = loss_fn(params, batch, cfg, None)
    np.testing.assert_allclose(float(m_i["ce"]), float(wm["ce"]), rtol=1e-5)
    g = jax.grad(
        lambda p: pipeline_loss(p, batch, cfg, mesh, num_microbatches=mb,
                                interleave=2)[0]
    )(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_interleave_validation(devices):
    cfg = CFG.replace(pp=2, dp=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(cfg, devices=devices[:4])
    with pytest.raises(ValueError, match="divisible by pp"):
        pipeline_loss(params, _batch(b=6), cfg, mesh,
                      num_microbatches=3, interleave=2)


@pytest.mark.slow
def test_pipeline_grad(devices):
    params = init_params(jax.random.PRNGKey(0), CFG)
    mesh = make_mesh(CFG)
    batch = _batch()
    g = jax.grad(
        lambda p: pipeline_loss(p, batch, CFG, mesh)[0]
    )(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


@pytest.mark.parametrize("use_pallas", [False, True], ids=["xla", "pallas"])
@pytest.mark.slow
def test_pipeline_with_ep_in_stage(use_pallas, devices):
    """PP x EP composition: experts shard over ep INSIDE each stage (the
    stage's MoE runs the in-shard_map all-to-all body), and the CE still
    matches the plain forward — including with the Pallas kernel body
    (interpret mode here; the production path on real TPU)."""
    cfg = CFG.replace(pp=2, dp=2, ep=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(cfg, devices=devices[:8], dp=2)
    batch = _batch(b=8)  # dp*ep*mb = 2*2*2
    total, m = pipeline_loss(params, batch, cfg, mesh, num_microbatches=2,
                             use_pallas=use_pallas)
    _, wm = loss_fn(params, batch, cfg, None)
    np.testing.assert_allclose(float(m["ce"]), float(wm["ce"]),
                               rtol=2e-5 if use_pallas else 1e-5)
    g = jax.grad(
        lambda p: pipeline_loss(p, batch, cfg, mesh, num_microbatches=2,
                                use_pallas=use_pallas)[0]
    )(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


def test_pipeline_vocab_gemm_is_conditional(devices):
    """Non-final ticks must skip the LM head: every vocab-sized GEMM in
    the lowered HLO must live in a computation reachable only from a
    ``conditional`` branch, never directly in the scan/while tick body
    (round-2 verdict weak #3)."""
    import re

    cfg = CFG.replace(pp=4, dp=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(cfg, devices=devices[:8])
    batch = _batch(b=4)
    txt = jax.jit(
        lambda p, b: pipeline_loss(p, b, cfg, mesh, num_microbatches=2)[0]
    ).lower(params, batch).as_text()  # StableHLO MLIR
    lines = txt.splitlines()

    # spans of stablehlo.if/case ops: all their regions, by brace balance
    spans = []
    for i, ln in enumerate(lines):
        if "stablehlo.if" in ln or "stablehlo.case" in ln:
            bal = 0
            for j in range(i, len(lines)):
                bal += lines[j].count("{") - lines[j].count("}")
                if j > i and bal <= 0:
                    spans.append((i, j))
                    break
    assert spans, "lax.cond was lowered away (no stablehlo.if/case)"

    v = cfg.vocab_size
    dot_lines = [
        i for i, ln in enumerate(lines)
        if "dot_general" in ln
        and re.search(rf"tensor<[\dx]*x{v}xf32>", ln)
    ]
    assert dot_lines, "vocab GEMM vanished from the HLO (test is stale)"
    for i in dot_lines:
        assert any(a < i < b for a, b in spans), (
            f"vocab GEMM at line {i} is outside every conditional region"
        )



def test_stage_stacking_validation():
    cfg = CFG.replace(num_layers=3, pp=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="not divisible"):
        stack_stage_params(params, cfg, 2)
    cfg2 = CFG.replace(moe_frequency=2)  # mixed dense/moe stages
    params2 = init_params(jax.random.PRNGKey(0), cfg2)
    with pytest.raises(ValueError, match="uniform"):
        stack_stage_params(params2, cfg2, 4)
