"""Planner CI gates: golden predicted-latency tables, error paths,
measured-override precedence, and byte-model consistency.

The golden comparison is the review gate the ISSUE asks for: any change
that moves a canonical prediction > 0.1% or flips a predicted winner
fails here and must ship a regenerated ``golden.json``
(``python -m flashmoe_tpu.planner --write-golden``) in the same PR.
"""

import json

import jax.numpy as jnp
import pytest

from flashmoe_tpu.analysis import a2a_transport_cost, path_costs
from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig
from flashmoe_tpu.planner.golden import (
    GOLDEN_D, GOLDEN_GENS, GOLDEN_RTOL, golden_snapshot, load_golden,
)
from flashmoe_tpu.planner.model import explain_table, predict_paths
from flashmoe_tpu.planner.select import (
    _cached_backend, resolve_moe_backend, select_path,
)
from flashmoe_tpu.utils.telemetry import metrics

REF = BENCH_CONFIGS["reference"]


@pytest.fixture(autouse=True)
def _hermetic(monkeypatch):
    """The model consults env knobs and caches; pin both per test."""
    for var in ("FLASHMOE_FUSED_BATCHED", "FLASHMOE_TUNING_FILE",
                "FLASHMOE_TPU_GEN", "FLASHMOE_BENCH_RECORDS",
                "FLASHMOE_MOCK_SLICES"):
        monkeypatch.delenv(var, raising=False)
    from flashmoe_tpu import tuning

    tuning._load.cache_clear()
    _cached_backend.cache_clear()
    yield
    tuning._load.cache_clear()
    _cached_backend.cache_clear()


# ----------------------------------------------------------------------
# Golden tables
# ----------------------------------------------------------------------

def test_golden_tables_match_model():
    """Recompute every golden prediction and compare: terms within
    GOLDEN_RTOL, winners and feasibility exactly — across every
    (config, generation, wire-dtype, chunk-count) point."""
    live, frozen = golden_snapshot(), load_golden()
    assert live["d"] == frozen["d"] == GOLDEN_D
    assert set(live["configs"]) == set(frozen["configs"])
    for cname, gens in frozen["configs"].items():
        for gen, wires in gens.items():
            for wname, chunks in wires.items():
                for chname, g in chunks.items():
                    l = live["configs"][cname][gen][wname][chname]
                    assert l["winner"] == g["winner"], (
                        f"predicted winner flipped for {cname}@{gen}"
                        f"[wire={wname},chunks={chname}]: "
                        f"{g['winner']} -> {l['winner']}; "
                        f"if intentional, regenerate with python -m "
                        f"flashmoe_tpu.planner --regen-golden and "
                        f"justify in the PR")
                    assert l["backend"] == g["backend"]
                    assert set(l["paths"]) == set(g["paths"])
                    for pname, terms in g["paths"].items():
                        lt = l["paths"][pname]
                        assert lt["feasible"] == terms["feasible"], (
                            cname, gen, wname, chname, pname)
                        for term, want in terms.items():
                            if term == "feasible":
                                continue
                            assert lt[term] == pytest.approx(
                                want, rel=GOLDEN_RTOL, abs=1e-9), (
                                f"{cname}@{gen}[{wname},{chname}]"
                                f"/{pname}.{term}")


def test_golden_tables_cover_wire_dimension():
    """CI gate for the knob dimension itself: every golden (config, gen)
    point must carry every GOLDEN_WIRES variant, so a future knob added
    to GOLDEN_WIRES cannot silently skip the CI-gated tables — and the
    compressed variant must actually be cheaper on the wire."""
    from flashmoe_tpu.planner.golden import GOLDEN_WIRES

    frozen = load_golden()
    assert set(GOLDEN_WIRES) >= {"off", "e4m3"}
    for cname, gens in frozen["configs"].items():
        for gen, wires in gens.items():
            assert set(wires) == set(GOLDEN_WIRES), (cname, gen)
            off = wires["off"]["serial"]["paths"]["collective"]
            on = wires["e4m3"]["serial"]["paths"]["collective"]
            assert on["ici_ms"] < off["ici_ms"], (cname, gen)
            assert on["hbm_ms"] < off["hbm_ms"], (cname, gen)
            # the fused rows are disqualified under compression
            for pname, terms in \
                    wires["e4m3"]["serial"]["paths"].items():
                if pname.startswith("fused"):
                    assert not terms["feasible"], (cname, gen, pname)


def test_golden_tables_cover_chunk_dimension():
    """CI gate for the chunked-pipeline dimension: every golden
    (config, gen, wire) point carries exactly the chunk variants the
    config supports (golden_chunk_variants — mixtral's nLx=1 at d=8
    cannot chunk), and on the multi-chip golden configs the chunked
    overlap-adjusted prediction must beat the serial one (the
    acceptance bar for the schedule's pricing)."""
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.planner.golden import (
        GOLDEN_CHUNKS, golden_chunk_variants,
    )

    frozen = load_golden()
    assert set(GOLDEN_CHUNKS) >= {"serial", "c4"}
    for cname, gens in frozen["configs"].items():
        want = set(golden_chunk_variants(BENCH_CONFIGS[cname]))
        for gen, wires in gens.items():
            for wname, chunks in wires.items():
                assert set(chunks) == want, (cname, gen, wname)
                if "c4" not in chunks:
                    continue
                ser = chunks["serial"]["paths"]
                c4 = chunks["c4"]["paths"]
                for pname in ("collective", "ragged"):
                    # chunking pays n x alpha on the wire but hides the
                    # exchange behind the FFN: total drops, ici rises
                    assert c4[pname]["total_ms"] < \
                        ser[pname]["total_ms"], (cname, gen, wname,
                                                 pname)
                    assert c4[pname]["ici_ms"] > \
                        ser[pname]["ici_ms"], (cname, gen, wname, pname)
                # fused rows are chunk-independent: identical pricing
                for pname, terms in ser.items():
                    if pname.startswith("fused"):
                        assert c4[pname] == terms, (cname, gen, wname,
                                                    pname)
    # mixtral (nLx=1 at d=8) must be the config that skips c4 — the
    # skip rule is exercised, not vacuous
    assert "c4" not in frozen["configs"]["mixtral"]["v5e"]["off"]
    assert "c4" in frozen["configs"]["reference"]["v5e"]["off"]


def test_golden_tables_cover_schedule_dimension():
    """CI gate for the fused-schedule axis (ISSUE 12): every golden
    point must carry a row for EVERY fused schedule — batched,
    resident, stream, AND rowwin — so a schedule added to the kernel
    cannot silently skip the CI-gated tables; and the mixtral verdict
    must be the recorded QUANTITATIVE race the rowwin schedule turned
    it into (a feasible fused[rowwin] row priced against the collective
    transports, whichever way selection lands), not the old categorical
    'no weights-once schedule feasible'."""
    frozen = load_golden()
    want = {"fused[batched]", "fused[resident]", "fused[stream]",
            "fused[rowwin]"}
    for cname, gens in frozen["configs"].items():
        for gen, wires in gens.items():
            for wname, chunks in wires.items():
                for chname, g in chunks.items():
                    assert want <= set(g["paths"]), (cname, gen, wname,
                                                     chname)
    mix = frozen["configs"]["mixtral"]["v5e"]["off"]["serial"]["paths"]
    assert mix["fused[rowwin]"]["feasible"]
    assert not mix["fused[batched]"]["feasible"]
    assert not mix["fused[resident]"]["feasible"]
    # the race is quantitative: the rowwin row carries a real latency,
    # and the recorded winner is whoever won it
    assert mix["fused[rowwin]"]["total_ms"] > 0
    winner = frozen["configs"]["mixtral"]["v5e"]["off"]["serial"]["winner"]
    assert winner in ("collective", "ragged", "fused[rowwin]",
                      "fused_combine")


def test_planted_vmem_infeasible_rowwin_row():
    """ISSUE 12 satellite: a config whose hidden size starves even the
    minimal (row tile, K-window) pair must surface as an
    infeasible-WITH-REASON fused[rowwin] planner row — never a crash,
    never a silently missing row."""
    cfg = MoEConfig(num_experts=8, expert_top_k=2,
                    hidden_size=2 ** 17, intermediate_size=2 ** 17,
                    sequence_len=128, capacity_factor=1.0,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    preds = {p.path: p for p in predict_paths(cfg, 8, "v5e")}
    row = preds["fused[rowwin]"]
    assert not row.feasible
    assert "rowwin infeasible" in row.note
    assert "VMEM" in row.note
    # every weights-once schedule is out too; the collective transports
    # remain the feasible fallback
    assert not preds["fused[batched]"].feasible
    assert preds["collective"].feasible


def test_d8_canonical_breakdown_all_generations():
    """The acceptance-criteria surface: at d=8 on every supported
    generation the reference config gets a full breakdown (compute,
    HBM, ICI, DCN, overlap-adjusted total) and a named feasible
    winner."""
    for gen in GOLDEN_GENS:
        preds = predict_paths(REF, 8, gen)
        assert {"collective", "ragged", "fused[batched]",
                "fused[resident]", "fused[stream]", "fused[rowwin]",
                "fused_combine"} <= {p.path for p in preds}
        winner = next(p for p in preds if p.feasible)
        assert winner.total_ms > 0
        for p in preds:
            assert p.compute_ms > 0 and p.hbm_ms > 0
            assert p.serial_ms >= max(p.compute_ms, p.hbm_ms)
            if p.feasible:
                assert p.total_ms <= p.serial_ms + 1e-9
        table = explain_table(preds)
        for col in ("compute ms", "HBM ms", "ICI ms", "DCN ms",
                    "predicted ms"):
            assert col in table


def test_cli_prints_table_and_winner(capsys):
    from flashmoe_tpu.planner.__main__ import main

    assert main(["--config", "reference", "--d", "8"]) == 0
    out = capsys.readouterr().out
    for gen in GOLDEN_GENS:
        assert f"gen={gen}" in out
    assert "predicted winner:" in out
    assert "| ICI ms | DCN ms |" in out


# ----------------------------------------------------------------------
# Error paths
# ----------------------------------------------------------------------

def test_unknown_generation_is_a_clean_valueerror():
    with pytest.raises(ValueError, match="v5e"):
        predict_paths(REF, 8, "v7x")
    from flashmoe_tpu.parallel.overlap import overlap_bound

    with pytest.raises(ValueError, match="supported"):
        overlap_bound(REF, 8, "cpu")


def test_divisibility_errors():
    with pytest.raises(ValueError, match="divisible"):
        predict_paths(REF, 6, "v5e")            # E=64 % 6 != 0
    with pytest.raises(ValueError, match="slices"):
        predict_paths(REF, 8, "v5e", slices=3)  # 8 % 3 != 0
    with pytest.raises(ValueError, match="inner"):
        a2a_transport_cost(8, 3, 1e6)           # ADVICE r5: no silent //


def test_mock_slices_garbage_is_loud_but_never_blocks_trace(monkeypatch):
    """Hardened mock parsing (ISSUE 13 satellite): garbage raises a
    ValueError naming the world size at the detection layer, while the
    planner's auto resolution — which must never die inside a trace —
    degrades to the single-slice flat pricing."""
    from flashmoe_tpu.parallel.topology import slice_structure
    from flashmoe_tpu.planner.select import resolve_moe_plan

    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "banana")
    with pytest.raises(ValueError, match="8 devices"):
        slice_structure(devices=list(range(8)))
    backend, _ = resolve_moe_plan(REF.replace(moe_backend="auto", ep=8))
    assert backend in ("collective", "ragged", "fused")
    monkeypatch.setenv("FLASHMOE_MOCK_SLICES", "2")
    assert slice_structure(devices=list(range(8))) == (2, 4)


# ----------------------------------------------------------------------
# Selection policy
# ----------------------------------------------------------------------

def test_predicted_winner_when_no_measurements():
    sel = select_path(REF, 8, "v5e", record=False)
    assert sel.mode == "predicted"
    assert sel.winner == sel.predicted_winner
    assert sel.measured == {} and sel.measured_ms is None


def test_measured_override_precedence():
    """A measured entry beats the prediction — even when the model
    disagrees — but never resurrects an infeasible path."""
    pred = select_path(REF, 8, "v5e", record=False)
    loser = ("fused" if pred.predicted_winner != "fused[batched]"
             else "collective")
    sel = select_path(REF, 8, "v5e", measured={loser: 0.001},
                      record=False)
    assert sel.mode == "measured" and sel.winner == loser
    assert sel.measured_ms == 0.001
    # infeasible family: measurement ignored, prediction stands
    mix = BENCH_CONFIGS["mixtral"]
    sel2 = select_path(mix, 8, "v5e", slices=2,   # fused: intra-slice only
                       measured={"fused": 0.001}, record=False)
    assert sel2.winner != "fused"


def test_measured_override_from_tuning_table(tmp_path, monkeypatch):
    from flashmoe_tpu import tuning

    tbl = tmp_path / "table.json"
    tbl.write_text(json.dumps({"generation": "v5e", "entries": [{
        "kernel": "path_latency",
        "match": {"path": "ragged", "h": REF.hidden_size,
                  "i": REF.intermediate_size, "d": 8},
        "measured_ms": 0.0005}]}))
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(tbl))
    tuning._load.cache_clear()
    got = tuning.measured_path_latencies(
        "v5e", h=REF.hidden_size, i=REF.intermediate_size, d=8)
    assert got == {"ragged": 0.0005}
    sel = select_path(REF, 8, "v5e", record=False)
    assert sel.mode == "measured" and sel.winner == "ragged"
    assert sel.backend == "ragged"


def test_measured_override_from_bench_records(tmp_path, monkeypatch):
    metric = (f"moe_layer_fwd_ms[x:E={REF.num_experts},"
              f"k={REF.expert_top_k},H={REF.hidden_size},"
              f"I={REF.intermediate_size},S={REF.tokens},bfloat16]")
    rec = {"metric": metric, "path": "collective", "value": 0.0007,
           "d": 8, "xla_path_ms": 0.009}
    p = tmp_path / "bench.jsonl"
    p.write_text("not json\n" + json.dumps(rec) + "\n")
    monkeypatch.setenv("FLASHMOE_BENCH_RECORDS", str(p))
    sel = select_path(REF, 8, "v5e", record=False)
    assert sel.mode == "measured" and sel.winner == "collective"
    # a single-chip record (bench's headline, d=1) must never override
    # an 8-rank selection — and vice versa (code-review finding)
    rec1 = dict(rec, d=1, path="explicit", value=0.0001)
    p.write_text(json.dumps(rec1) + "\n")
    sel1 = select_path(REF, 8, "v5e", record=False)
    assert sel1.mode == "predicted"


def test_selection_decision_lands_in_telemetry():
    n0 = len(metrics.decisions)
    sel = select_path(REF, 8, "v5e")
    assert len(metrics.decisions) == n0 + 1
    rec = metrics.last_decision("planner.path_select")
    assert rec["winner"] == sel.winner
    assert rec["mode"] == "predicted"
    assert {"compute_ms", "hbm_ms", "ici_ms", "dcn_ms",
            "total_ms"} <= set(rec["breakdown"][0])
    assert metrics.counters["decision.planner.path_select"] >= 1


def test_auto_backend_resolution(monkeypatch):
    cfg = REF.replace(moe_backend="auto", ep=8)
    backend = resolve_moe_backend(cfg)
    assert backend in ("collective", "ragged", "fused")
    # explicit configs pass through untouched (no planner involved)
    assert resolve_moe_backend(REF.replace(moe_backend="fused",
                                           ep=8)) == "fused"
    # tp > 1 short-circuits to the only composing transport
    assert resolve_moe_backend(
        REF.replace(moe_backend="auto", ep=4, tp=2)) == "collective"
    # shared experts can never land on the ragged layer
    ds = BENCH_CONFIGS["deepseek"].replace(moe_backend="auto")
    assert resolve_moe_backend(ds) in ("collective", "fused")


# ----------------------------------------------------------------------
# Consistency with the analysis byte model
# ----------------------------------------------------------------------

def test_planner_bytes_agree_with_analysis():
    """The planner never re-derives bytes: every row's PathCost must be
    exactly what analysis.path_costs prices for that path."""
    d = 8
    byte_path = {"collective": ("explicit", None),
                 "hierarchical": ("explicit", None),
                 "ragged": ("ragged", None),
                 "fused[batched]": ("fused", "batched"),
                 "fused[resident]": ("fused", "resident"),
                 "fused[stream]": ("fused", "stream"),
                 "fused[rowwin]": ("fused", "rowwin"),
                 "fused_combine": ("fused_combine", None)}
    for p in predict_paths(REF, d, "v5e", slices=2):
        ap, sched = byte_path[p.path]
        want = path_costs(REF, ap, d_world=d, schedule=sched)
        assert p.cost.total_bytes == want.total_bytes, p.path
        assert p.cost.flops == want.flops


def test_fused_combine_return_bytes_not_overstated():
    """ADVICE r5 satellite: at capacity_factor > 1 the sorted-return
    combine sends only the routed rows back, so its comm must be
    strictly below the slab path's."""
    cfg = REF.replace(capacity_factor=2.0)
    fc = path_costs(cfg, "fused_combine", d_world=8)
    fu = path_costs(cfg, "fused", d_world=8)
    assert fc.comm_bytes < fu.comm_bytes
    # and at cf=1 the two coincide (slots == rows)
    assert path_costs(REF, "fused_combine", d_world=8).comm_bytes == \
        path_costs(REF, "fused", d_world=8).comm_bytes


def test_single_chip_paths_and_bench_fields(monkeypatch):
    preds = predict_paths(REF, 1, "v5e")
    assert {p.path for p in preds} == {"xla", "explicit", "gather"}
    assert all(p.ici_ms == 0 and p.dcn_ms == 0 for p in preds)
    # training excludes the inference-only gather kernel
    tr = predict_paths(REF.replace(is_training=True), 1, "v5e")
    assert not next(p for p in tr if p.path == "gather").feasible

    import bench

    monkeypatch.setenv("FLASHMOE_TPU_GEN", "v5e")
    bench._PARTIAL.clear()
    fields = bench._planner_fields(REF, 1e-3, 2e-3)
    assert fields["planner_gen"] == "v5e"
    assert fields["predicted_path"] == "explicit"
    assert "predicted_ms" in fields and "prediction_error" in fields
    assert "xla_prediction_error" in fields
    assert fields["predicted_winner"] in ("explicit", "gather", "xla")


def test_hierarchical_beats_flat_on_dcn_messages():
    """Multi-slice: the two-stage path's whole point is fewer DCN
    alpha payments; at small slabs it must predict faster than flat
    collective."""
    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=2048,
                    capacity_factor=1.0, dtype=jnp.bfloat16)
    preds = {p.path: p for p in predict_paths(cfg, 16, "v5e", slices=4)}
    assert preds["hierarchical"].dcn_ms < preds["collective"].dcn_ms
    assert not preds["fused[batched]"].feasible  # intra-slice only


# ----------------------------------------------------------------------
# Multi-slice scale-out (ISSUE 13): per-hop wires, DP allreduce,
# EP-vs-DP-across-DCN trade, golden slices dimension
# ----------------------------------------------------------------------

def test_hierarchical_dcn_wire_shrinks_dcn_term_only():
    """wire_dtype_dcn prices the DCN hop at the fp8 row size: the
    hierarchical row's dcn_ms shrinks, its ici_ms is untouched, and
    the flat row never sees the knob (no re-encode hop)."""
    base = {p.path: p for p in predict_paths(REF, 8, "v5e", slices=4)}
    dcn = {p.path: p for p in predict_paths(
        REF.replace(wire_dtype_dcn="e4m3"), 8, "v5e", slices=4)}
    assert dcn["hierarchical"].dcn_ms < base["hierarchical"].dcn_ms
    assert dcn["hierarchical"].ici_ms == base["hierarchical"].ici_ms
    assert dcn["collective"].dcn_ms == base["collective"].dcn_ms
    assert "dcn:e4m3" in dcn["hierarchical"].wire
    # the fused rows are disqualified under any wire, dcn included
    for pname, p in dcn.items():
        if pname.startswith("fused"):
            assert not p.feasible, pname


def test_dcn_wire_discount_not_priced_at_one_rank_per_slice():
    """slices == d degenerates the two-stage exchange to flat (the
    layer gates on 1 < dcn_inner < d), so the planner must not price
    the DCN-wire discount there."""
    base = {p.path: p for p in predict_paths(REF, 8, "v5e", slices=8)}
    dcn = {p.path: p for p in predict_paths(
        REF.replace(wire_dtype_dcn="e4m3"), 8, "v5e", slices=8)}
    assert dcn["hierarchical"].dcn_ms == base["hierarchical"].dcn_ms
    assert "inert" in dcn["hierarchical"].note


def test_dp_allreduce_priced_from_decider_ring_model():
    """The DP axis's gradient ring (decider.ring_allreduce_ms): 0 for
    inference/dp=1, DCN pricing > ICI pricing, and the term rides every
    row of a prediction set identically (never flips a path winner)."""
    from flashmoe_tpu.planner.model import dp_allreduce_ms

    tr = REF.replace(is_training=True)
    assert dp_allreduce_ms(REF, 4, "v5e") == 0.0          # inference
    assert dp_allreduce_ms(tr, 1, "v5e") == 0.0           # no dp axis
    ici = dp_allreduce_ms(tr, 4, "v5e", over_dcn=False)
    dcn = dp_allreduce_ms(tr, 4, "v5e", over_dcn=True)
    assert 0.0 < ici < dcn
    preds = predict_paths(tr, 8, "v5e", dp=4, dp_over_dcn=True)
    assert all(p.dp_allreduce_ms == pytest.approx(dcn) for p in preds)
    bare = {p.path: p.total_ms for p in predict_paths(tr, 8, "v5e")}
    for p in preds:
        assert p.total_ms == pytest.approx(bare[p.path] + dcn, rel=1e-6)


def test_scaleout_plan_trades_ep_against_dp_across_dcn():
    """The EP-vs-DP-across-DCN trade: a training job with a heavy
    gradient keeps the DP ring off DCN (ep_across_dcn); the same job in
    inference mode — no allreduce at all — packs the a2a inside a slice
    (dp_across_dcn).  Both mappings priced, loser recorded."""
    from flashmoe_tpu.planner.select import scaleout_plan

    cfg = REF.replace(ep=8)
    train = scaleout_plan(cfg.replace(is_training=True), 32, 4, "v5e",
                          record=False)
    assert train.mapping == "ep_across_dcn"
    assert (train.ep, train.dp) == (8, 4)
    assert train.a2a_slices == 4 and not train.dp_over_dcn
    assert train.alternative_ms is not None
    assert train.predicted_ms < train.alternative_ms
    infer = scaleout_plan(cfg, 32, 4, "v5e", record=False)
    assert infer.mapping == "dp_across_dcn"
    assert infer.a2a_slices == 1 and infer.dp_over_dcn
    with pytest.raises(ValueError, match="slices"):
        scaleout_plan(cfg, 32, 5, "v5e", record=False)


def test_scaleout_decision_lands_in_telemetry():
    from flashmoe_tpu.planner.select import scaleout_plan

    scaleout_plan(REF.replace(ep=8), 32, 4, "v5e")
    rec = metrics.last_decision("planner.scaleout")
    assert rec is not None and rec["mapping"] in ("ep_across_dcn",
                                                  "dp_across_dcn")
    assert rec["n_slices"] == 4 and rec["predicted_ms"] > 0


def test_golden_slices_dimension_gates_dcn_wire():
    """The golden `slices` dimension (ISSUE 13 acceptance): every
    (config, gen) point freezes the planner's picks at 1/2/4/8 slices,
    matches the live model, and at the 4-slice point the
    hierarchical+e4m3-DCN-hop row beats flat-uncompressed on modeled
    DCN ms."""
    from flashmoe_tpu.planner.golden import GOLDEN_SLICES, golden_snapshot

    live, frozen = golden_snapshot(), load_golden()
    assert set(live["slices"]) == set(frozen["slices"])
    for cname, gens in frozen["slices"].items():
        for gen, points in gens.items():
            assert set(points) == {str(s) for s in GOLDEN_SLICES}
            for s, g in points.items():
                l = live["slices"][cname][gen][s]
                for plan_key in ("plan", "plan_dcn"):
                    assert l[plan_key]["winner"] == g[plan_key]["winner"], (
                        f"slices winner flipped for {cname}@{gen}"
                        f"[slices={s},{plan_key}]: "
                        f"{g[plan_key]['winner']} -> "
                        f"{l[plan_key]['winner']}; regenerate with "
                        f"python -m flashmoe_tpu.planner --regen-golden")
                    assert l[plan_key]["chunks"] == g[plan_key]["chunks"]
                    assert l[plan_key]["total_ms"] == pytest.approx(
                        g[plan_key]["total_ms"], rel=GOLDEN_RTOL)
                for term in ("flat_dcn_ms", "hier_dcn_ms"):
                    if g[term] is None:
                        assert l[term] is None and s == "1"
                    else:
                        assert l[term] == pytest.approx(
                            g[term], rel=GOLDEN_RTOL)
                assert l["hier_dcn_wins"] == g["hier_dcn_wins"]
            # THE acceptance criterion: 4-slice mesh, fp8 DCN hop +
            # per-slice-pair aggregation beats flat-uncompressed
            p4 = points["4"]
            assert p4["hier_dcn_wins"] is True, (cname, gen)
            assert p4["hier_dcn_ms"] < p4["flat_dcn_ms"], (cname, gen)


def test_select_path_keys_measurements_on_dcn_wire(tmp_path,
                                                   monkeypatch):
    """A latency measured with the DCN-hop wire on never overrides a
    selection without it (and vice versa) — the wire_dcn key rides the
    measurement identity like wire/wire_combine/chunks."""
    import json as _json

    rec = {"metric": f"moe_layer_fwd_ms[x:E={REF.num_experts},"
                     f"k={REF.expert_top_k},H={REF.hidden_size},"
                     f"I={REF.intermediate_size},S={REF.tokens},"
                     f"bfloat16]",
           "value": 0.001, "path": "collective", "d": 8,
           "wire_dtype": "off", "wire_dtype_combine": "off",
           "wire_dtype_dcn": "e4m3"}
    p = tmp_path / "records.jsonl"
    p.write_text(_json.dumps(rec) + "\n")
    monkeypatch.setenv("FLASHMOE_BENCH_RECORDS", str(p))
    sel_off = select_path(REF, 8, "v5e", record=False)
    assert sel_off.mode == "predicted"       # dcn-wire record ignored
    sel_on = select_path(REF.replace(wire_dtype_dcn="e4m3"), 8, "v5e",
                         record=False)
    assert sel_on.mode == "measured"
    assert sel_on.measured_ms == pytest.approx(0.001)


# ----------------------------------------------------------------------
# Speculative decoding economics (ISSUE 20)
# ----------------------------------------------------------------------

def test_golden_tables_cover_speculate_dimension():
    """CI gate for the speculation axis: every golden (config, gen)
    point carries the k=GOLDEN_SPEC_K verify pricing, the uplift at
    the golden acceptance beats 1x, and the break-even acceptance sits
    below the golden acceptance — speculation must PAY at the golden
    point, or the regenerated table fails review here."""
    from flashmoe_tpu.planner.golden import (
        GOLDEN_CONFIGS, GOLDEN_SPEC_ACCEPT, GOLDEN_SPEC_K,
    )

    frozen = load_golden()
    assert set(frozen["speculate"]) == set(GOLDEN_CONFIGS)
    for cname, gens in frozen["speculate"].items():
        assert set(gens) == set(GOLDEN_GENS), cname
        for gen, pt in gens.items():
            assert pt["verify_tokens"] == GOLDEN_SPEC_K
            assert pt["accept_rate"] == GOLDEN_SPEC_ACCEPT
            # the verify span must price as a span, not k+1 steps
            assert 1.0 <= pt["cost_ratio"] < GOLDEN_SPEC_K + 1
            assert pt["uplift"] > 1.0, (cname, gen)
            assert pt["break_even_accept"] < GOLDEN_SPEC_ACCEPT, \
                (cname, gen)
            assert pt["pays"] is True, (cname, gen)


def test_speculate_model_math():
    """E[n] closed form, bisection break-even, and the verify_tokens
    pricing axis on decode shapes."""
    from flashmoe_tpu.planner.model import (
        decode_shape, predict_paths, speculate_break_even,
        speculate_tokens_per_step, speculate_uplift,
    )

    cfg = BENCH_CONFIGS["reference"].replace(ep=8)
    # E[n](p) = (1 - p^(k+1)) / (1 - p); exact at the endpoints
    assert speculate_tokens_per_step(0.0, 3) == pytest.approx(1.0)
    assert speculate_tokens_per_step(1.0, 3) == pytest.approx(4.0)
    assert speculate_tokens_per_step(0.5, 3) == pytest.approx(1.875)
    # verify_tokens multiplies decode tokens AFTER d-rounding
    s1 = decode_shape(cfg, 8, decode_tokens=64)
    s4 = decode_shape(cfg, 8, decode_tokens=64, verify_tokens=3)
    assert s4.tokens == 4 * s1.tokens
    up = speculate_uplift(cfg, 8, "v5e", decode_tokens=64,
                          verify_tokens=3, accept_rate=0.7)
    assert up["cost_ratio"] == pytest.approx(
        up["tk_ms"] / up["t1_ms"])
    assert up["uplift"] == pytest.approx(
        up["tokens_per_step"] / up["cost_ratio"])
    be = speculate_break_even(cfg, 8, "v5e", decode_tokens=64,
                              verify_tokens=3)
    # the break-even acceptance exactly repays the verify span
    eq = speculate_uplift(cfg, 8, "v5e", decode_tokens=64,
                          verify_tokens=3, accept_rate=be)
    assert eq["uplift"] == pytest.approx(1.0, abs=1e-6)
    with pytest.raises(ValueError, match="verify_tokens"):
        decode_shape(cfg, 8, verify_tokens=-1)
    with pytest.raises(ValueError, match="decode"):
        predict_paths(cfg, 8, "v5e", verify_tokens=3)  # not decode mode


def test_select_path_spec_measurement_identity(tmp_path, monkeypatch):
    """The spec tag rides the measured-latency shape key: a spec=off
    tuning entry must never price a verify-span selection, and
    vice versa."""
    import json as _json

    from flashmoe_tpu import tuning
    from flashmoe_tpu.planner.select import (
        _shape_key, select_path, spec_tag,
    )

    assert spec_tag(None) == "off" and spec_tag(3) == "v3"
    cfg = BENCH_CONFIGS["reference"].replace(ep=8)
    key_off = _shape_key(cfg, 8)
    key_on = _shape_key(cfg, 8, spec="v3")
    assert key_off["spec"] == "off" and key_on["spec"] == "v3"
    assert {k: v for k, v in key_on.items() if k != "spec"} \
        == {k: v for k, v in key_off.items() if k != "spec"}
    # a measured entry tagged spec=off only matches the off selection
    # (the decode selection keys on the DECODE-shaped config: s = the
    # per-step token count, not the training sequence)
    from flashmoe_tpu.planner.model import decode_shape

    dkey = _shape_key(decode_shape(cfg, 8, 64), 8)
    path = str(tmp_path / "v5e.json")
    with open(path, "w") as f:
        _json.dump({"generation": "v5e", "entries": [
            {"kernel": "path_latency",
             "match": dict(dkey, path="collective"),
             "measured_ms": 0.001}]}, f)
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", path)
    tuning._load.cache_clear()
    sel_off = select_path(cfg, 8, "v5e", mode="decode",
                          decode_tokens=64, record=False)
    sel_on = select_path(cfg, 8, "v5e", mode="decode",
                         decode_tokens=64, verify_tokens=3,
                         record=False)
    assert sel_off.mode == "measured"
    assert sel_on.mode == "predicted"
