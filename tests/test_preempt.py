"""Preemption-safe training: listener, graceful drain, async saves,
deterministic data resume (docs/RESILIENCE.md, preemption section)."""

import itertools
import os
import signal
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime import checkpoint as ckpt
from flashmoe_tpu.runtime.data import TokenLoader, write_token_file
from flashmoe_tpu.runtime.preempt import PreemptionListener
from flashmoe_tpu.runtime.resilient import (
    ResilienceConfig, resilient_train, supervise,
)
from flashmoe_tpu.runtime.trainer import (
    init_state, make_optimizer, make_train_step, state_shardings,
)
from flashmoe_tpu.utils.telemetry import Metrics

CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=32, num_layers=1,
                moe_frequency=1, vocab_size=256, num_heads=2,
                drop_tokens=False, is_training=True, ep=1,
                dtype=jnp.float32, param_dtype=jnp.float32)


# one compiled step shared across the module: these tests exercise the
# HOST-side drain/resume machinery, not XLA — one compile pays for all
_SHARED: dict = {}


def _fixture(devices):
    if not _SHARED:
        mesh = make_mesh(CFG, dp=1, devices=devices[:1])
        opt = make_optimizer(CFG, total_steps=8)
        _SHARED["v"] = (make_train_step(CFG, mesh, opt), opt, mesh)
    step, opt, mesh = _SHARED["v"]
    state = init_state(jax.random.PRNGKey(0), CFG, opt)
    state = jax.device_put(state, state_shardings(state, CFG, mesh))
    return state, step


def _batches():
    k = itertools.count()
    while True:
        yield {"tokens": jax.random.randint(
            jax.random.PRNGKey(next(k)), (2, 33), 0, 256)}


def _token_loader(tmp_path, windows=24, batch=2, seed=7):
    p = str(tmp_path / "tokens.bin")
    if not os.path.exists(p):
        rng = np.random.default_rng(seed)
        write_token_file(p, rng.integers(0, 256, size=windows * 33,
                                         dtype=np.int32))
    return TokenLoader(p, batch, 32, seed=seed, native=False)


# ----------------------------------------------------------------------
# Listener
# ----------------------------------------------------------------------

def test_listener_programmatic_notice():
    pl = PreemptionListener(grace_s=5.0)
    assert not pl.requested
    assert pl.notice_age_s() is None and pl.remaining_grace_s() is None
    pl.notify("test")
    assert pl.requested and pl.source == "test"
    assert 0 <= pl.notice_age_s() < 5.0
    assert pl.remaining_grace_s() <= 5.0
    t0 = pl.notice_age_s()
    pl.notify("again")  # idempotent: first notice keeps the clock
    assert pl.source == "test"
    assert pl.notice_age_s() >= t0
    pl.clear()
    assert not pl.requested and pl.source is None


def test_listener_signal_install_uninstall():
    pl = PreemptionListener()
    prev = signal.getsignal(signal.SIGUSR1)
    with pl.install(signals=(signal.SIGUSR1,)) as listener:
        os.kill(os.getpid(), signal.SIGUSR1)
        assert listener.wait(timeout=5.0)
        assert listener.requested and listener.source == "SIGUSR1"
    assert signal.getsignal(signal.SIGUSR1) is prev
    pl.uninstall()  # idempotent


# ----------------------------------------------------------------------
# Graceful drain (the fast chaos smoke: armed preempt fault drains a
# checkpoint + loader state within the grace window)
# ----------------------------------------------------------------------

def test_preempt_smoke_drains_checkpoint_and_loader_state(devices,
                                                          tmp_path):
    from flashmoe_tpu.chaos import FaultPlan, make_injector

    state, step = _fixture(devices)
    loader = _token_loader(tmp_path)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=100)
    pl = PreemptionListener(grace_s=30.0)
    injector = make_injector(FaultPlan("preempt", step=2), rcfg,
                             preempt=pl)
    metrics = Metrics()
    t0 = time.perf_counter()
    final, hist = resilient_train(state, step, loader, num_steps=6,
                                  rcfg=rcfg, metrics=metrics,
                                  fail_injector=injector, preempt=pl)
    drain_s = time.perf_counter() - t0
    # the in-flight step (2) finished, then the loop drained
    assert int(final.step) == 3
    assert len(hist) == 3
    assert metrics.counters["preempt_drains"] == 1
    d = metrics.last_decision("preempt.drain")
    assert d is not None and d["step"] == 3 and d["source"] == "chaos"
    assert d["remaining_grace_s"] > 0
    assert drain_s < pl.grace_s
    # final checkpoint + loader cursor are durable at the drained step
    assert ckpt.latest_step(rcfg.checkpoint_dir) == 3
    assert ckpt.verify(rcfg.checkpoint_dir, 3)
    ls = ckpt.load_loader_state(rcfg.checkpoint_dir, 3)
    assert ls is not None and ls["epoch"] * 24 + ls["cursor"] == 3 * 2


def test_drain_resume_consumes_exact_stream(devices, tmp_path):
    """The acceptance bar: a preempt-resume run's loss history equals
    the uninterrupted run's bit-for-bit over the same step range."""
    # uninterrupted reference
    state, step = _fixture(devices)
    rcfg_a = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck_a"),
                              checkpoint_every=2)
    final_a, hist_a = resilient_train(state, step, _token_loader(tmp_path),
                                      num_steps=6, rcfg=rcfg_a)
    assert int(final_a.step) == 6

    # preempted at step 3, then resumed in a "fresh process"
    state, step = _fixture(devices)
    rcfg_b = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck_b"),
                              checkpoint_every=2)
    pl = PreemptionListener()

    def poke(i):
        if i == 3:
            pl.notify("test")

    mid, hist_b1 = resilient_train(state, step, _token_loader(tmp_path),
                                   num_steps=6, rcfg=rcfg_b,
                                   fail_injector=poke, preempt=pl)
    drained = int(mid.step)
    assert drained < 6
    state2, _ = _fixture(devices)  # fresh step-0 state, fresh loader
    final_b, hist_b2 = resilient_train(state2, step,
                                       _token_loader(tmp_path),
                                       num_steps=6, rcfg=rcfg_b)
    assert int(final_b.step) == 6
    hist_b = hist_b1 + hist_b2
    assert len(hist_b) == len(hist_a) == 6
    for a, b in zip(hist_a, hist_b):
        assert a["loss"] == b["loss"]  # bit-exact, not approx


def test_drain_skips_duplicate_save_at_checkpoint_boundary(devices,
                                                           tmp_path):
    state, step = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2)
    pl = PreemptionListener()

    def poke(i):
        if i == 1:
            pl.notify("test")

    metrics = Metrics()
    final, _ = resilient_train(state, step, _token_loader(tmp_path),
                               num_steps=6, rcfg=rcfg, metrics=metrics,
                               fail_injector=poke, preempt=pl)
    # drained at 2 right after the periodic save at 2: one checkpoint,
    # not a duplicate
    assert int(final.step) == 2
    assert metrics.counters["checkpoints"] == 1


# ----------------------------------------------------------------------
# Supervisor: drain -> restart -> exact continuation
# ----------------------------------------------------------------------

def test_supervise_resumes_after_drain(devices, tmp_path):
    """Drain -> restart -> exact continuation; the restart also clears
    stale path demotions (self-healing satellite: a blacklist earned on
    a dead topology must not outlive it — ``controller.demotion_reset``
    fires on the elastic resume)."""
    from flashmoe_tpu.planner.select import (
        failed_backends, report_path_failure, reset_path_failures,
    )

    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2)
    pl = PreemptionListener()
    fired = {"n": 0}

    def poke(i):
        if i == 3 and not fired["n"]:
            fired["n"] = 1
            # the dying incarnation demoted a path on its old topology
            report_path_failure("fused", "test: stale demotion")
            pl.notify("test")

    metrics = Metrics()
    try:
        final, hist = supervise(
            CFG, lambda fcfg: _token_loader(tmp_path), 6, rcfg,
            metrics=metrics, preempt=pl,
            devices_fn=lambda: jax.devices()[:1], fail_injector=poke)
        assert int(final.step) == 6
        assert len(hist) == 6  # drain loses zero steps
        assert metrics.counters["preempt_drains"] == 1
        assert metrics.counters["preempt_restarts"] == 1
        d = metrics.last_decision("supervisor.resume")
        assert d is not None and d["step"] == 4 and d["world"] == 1
        assert metrics.counters["loader_restores"] == 1
        assert not pl.requested  # latch cleared for the new incarnation
        # the resume wiped the pre-restart blacklist and said so
        assert failed_backends() == frozenset()
        dr = metrics.last_decision("controller.demotion_reset")
        assert dr is not None and dr["dropped"] == ["fused"]
        assert dr["world"] == 1
    finally:
        reset_path_failures()



def test_supervise_live_plane_healthz(devices, tmp_path):
    """`supervise(telemetry_port=0)` serves ONE /healthz across the
    job with step progress (the shared `steps` counter), the SLO
    episode state, and the checkpoint frontier (PR 13 live plane:
    supervise hands its own watchdog down so /healthz and the inner
    loop judge the same episodes)."""
    import json as _json
    import urllib.request

    from flashmoe_tpu.profiler.slo import SLOConfig

    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck_lp"),
                            checkpoint_every=2)
    metrics = Metrics()
    seen = {}

    def probe(i):
        if i == 3 and "hz" not in seen:
            start = metrics.last_decision("telemetry.server_start")
            url = f"http://127.0.0.1:{start['port']}/healthz"
            with urllib.request.urlopen(url, timeout=5) as r:
                seen["hz"] = _json.loads(r.read().decode())

    final, _ = supervise(
        CFG, lambda fcfg: _token_loader(tmp_path), 4, rcfg,
        metrics=metrics, devices_fn=lambda: jax.devices()[:1],
        fail_injector=probe, telemetry_port=0,
        slo=SLOConfig(step_ms=1e9))
    assert int(final.step) == 4
    hz = seen["hz"]
    assert hz["phase"] == "supervise" and hz["incarnation"] == 0
    assert hz["steps_done"] == 3          # live progress mid-run
    assert hz["last_checkpoint_step"] == 2
    assert hz["slo"]["budgets"] == {"step_ms": 1e9}
    assert hz["slo"]["in_breach"] == []
    names = [d["decision"] for d in metrics.decisions]
    assert names.count("telemetry.server_start") == 1
    assert names.count("telemetry.server_stop") == 1
