"""Model presets build, shrink, and run through the layer stack."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.models.presets import PRESETS
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops.moe import moe_layer


def test_all_presets_valid():
    for name, fn in PRESETS.items():
        cfg = fn()
        assert cfg.num_experts >= 1, name
        assert cfg.expert_capacity > 0, name


@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_layer_runs_small(name):
    """Each family's layer structure runs end-to-end at toy size."""
    cfg = PRESETS[name](
        hidden_size=128, intermediate_size=128, sequence_len=64,
        num_layers=2, vocab_size=512, num_heads=4, num_kv_heads=0,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    if cfg.num_experts > 16:
        cfg = cfg.replace(num_experts=16,
                          expert_top_k=min(cfg.expert_top_k, 16))
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 128),
                          jnp.float32)
    out = moe_layer(params, x, cfg, use_pallas=False)
    assert np.isfinite(np.asarray(out.out)).all()
    if not cfg.drop_tokens:
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
        )


@pytest.mark.slow
def test_weak_scaling_256_bench_config(devices):
    """BASELINE config #5 (256-expert weak-scaling / payload-skew) must be
    driver-invokable by name (bench.py --config weak_scaling_256) and
    correct: the full 256-expert routing runs through the collective EP
    layer on the virtual 8-device mesh at shrunken H/I/S, matching the
    dense oracle."""
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.parallel.ep import ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh

    cfg = BENCH_CONFIGS["weak_scaling_256"].replace(
        hidden_size=128, intermediate_size=128, sequence_len=1024,
        ep=8, drop_tokens=False, capacity_factor=1.0,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    assert cfg.num_experts == 256
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:8])
    out = ep_moe_layer(params, x, cfg, mesh)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=3e-4, atol=3e-4
    )
