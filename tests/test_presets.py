"""Model presets build, shrink, and run through the layer stack."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.models.presets import PRESETS
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops.moe import moe_layer


def test_all_presets_valid():
    for name, fn in PRESETS.items():
        cfg = fn()
        assert cfg.num_experts >= 1, name
        assert cfg.expert_capacity > 0, name


@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_layer_runs_small(name):
    """Each family's layer structure runs end-to-end at toy size."""
    cfg = PRESETS[name](
        hidden_size=128, intermediate_size=128, sequence_len=64,
        num_layers=2, vocab_size=512, num_heads=4, num_kv_heads=0,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    if cfg.num_experts > 16:
        cfg = cfg.replace(num_experts=16,
                          expert_top_k=min(cfg.expert_top_k, 16))
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (cfg.tokens, 128),
                          jnp.float32)
    out = moe_layer(params, x, cfg, use_pallas=False)
    assert np.isfinite(np.asarray(out.out)).all()
    if not cfg.drop_tokens:
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
        )
