"""Phase-level profiler: timeline mechanics, cost ledger, Perfetto
export schema, SLO watchdog, flight-ring offset export, and crash
postmortem bundles (docs/OBSERVABILITY.md, phase-profiler sections)."""

import json
import time

import jax
import jax.numpy as jnp
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.profiler import spans
from flashmoe_tpu.profiler.export import (
    trace_document, validate_trace, write_trace,
)
from flashmoe_tpu.profiler.ledger import (
    PHASES, ledger_config, phase_ledger, predicted_phase_ms,
    run_ledger_matrix,
)
from flashmoe_tpu.profiler.slo import (
    SLOConfig, SLOWatchdog, _parse_flat_yaml,
)
from flashmoe_tpu.profiler.spans import PhaseTimeline, merged_phase
from flashmoe_tpu.utils.telemetry import (
    FlightRecorder, Metrics, metrics as global_metrics, trace_span,
)


# ----------------------------------------------------------------------
# timeline mechanics (pure host)
# ----------------------------------------------------------------------

def test_merged_phase():
    assert merged_phase("moe.expert.3") == "moe.expert"
    assert merged_phase("moe.expert") == "moe.expert"
    assert merged_phase("moe.a2a_dispatch.12") == "moe.a2a_dispatch"
    assert merged_phase("train.step") == "train.step"


def test_timeline_records_spans_only_inside_steps():
    tl = PhaseTimeline(label="t")
    with spans.profiling(tl):
        # outside any step: trace_span must record nothing (jit
        # TRACE-time spans would otherwise pollute the data)
        with trace_span("moe.gate"):
            pass
        assert tl.spans == []
        tl.begin_step(0)
        with trace_span("moe.gate"):
            time.sleep(0.002)
        with trace_span("moe.expert.0"):
            pass
        with trace_span("moe.expert.1"):
            pass
        rec = tl.end_step()
    # unarmed again: spans silently off
    with trace_span("moe.gate"):
        pass
    assert [s["name"] for s in tl.spans] == \
        ["moe.gate", "moe.expert.0", "moe.expert.1"]
    # chunked sub-spans merge onto their base phase in the step totals
    assert set(rec["phases"]) == {"moe.gate", "moe.expert"}
    assert rec["phases"]["moe.gate"] >= 2.0  # ms
    assert rec["wall_ms"] >= rec["phases"]["moe.gate"]
    assert tl.phase_means()["moe.gate"] == rec["phases"]["moe.gate"]


def test_timeline_sections_and_counters_without_steps():
    tl = PhaseTimeline()
    with spans.profiling(tl):
        with spans.section("train.data_pull", step=7):
            pass
        tl.counter("moe.load_imbalance", 2.5, step=7)
    assert tl.sections[0]["name"] == "train.data_pull"
    assert tl.sections[0]["step"] == 7
    assert tl.counters[0]["value"] == 2.5
    # no timeline armed: section() is a free nullcontext
    with spans.section("train.data_pull"):
        pass
    assert len(tl.sections) == 1


def test_fence_is_noop_without_timeline_and_blocks_with():
    x = jnp.ones((4,))
    assert spans.fence(x) is x
    tl = PhaseTimeline()
    with spans.profiling(tl):
        assert spans.fence((x, {"a": x})) is not None


# ----------------------------------------------------------------------
# predicted per-phase costs + ledger join
# ----------------------------------------------------------------------

def test_predicted_phase_ms_positive_all_phases():
    cfg = ledger_config(2)
    for path in ("collective", "ragged"):
        pred = predicted_phase_ms(cfg, d=2, gen="v5e", path=path)
        assert set(pred) == set(PHASES)
        assert all(v > 0 for v in pred.values()), pred
    # single chip: no exchange legs priced
    pred1 = predicted_phase_ms(cfg, d=1, gen="v5e")
    assert set(pred1) == {"moe.gate", "moe.expert"}


def test_phase_ledger_joins_and_records_decisions():
    cfg = ledger_config(2)
    tl = PhaseTimeline(label="fabricated")
    tl.begin_step(0)
    for ph in PHASES:
        tok = tl.span_enter(ph)
        tl.span_exit(ph, tok)
    tl.end_step()
    n0 = len(global_metrics.decisions)
    rows, overlap = phase_ledger(tl, cfg, d=2, gen="v5e",
                                 path="collective", warn=False)
    assert [r["phase"] for r in rows] == list(PHASES)
    assert overlap is None  # no overlapped_ms on the timeline
    new = [d for d in global_metrics.decisions[n0:]
           if d["decision"] == "planner.phase_drift"]
    assert len(new) == len(PHASES)
    assert {d["phase"] for d in new} == set(PHASES)
    for r in rows:
        assert r["predicted_ms"] > 0
        assert "rel_error" in r and "exceeded" in r


def test_ledger_matrix_quick_point_end_to_end(devices, tmp_path):
    """The fast-lane acceptance point: flat x serial x wire-off profiled
    EAGERLY on the virtual mesh with profile_phases=True — all four
    phases measured, the per-step phase sum bounded by the step wall
    time, artifacts written and schema-valid, `observe --ledger`
    renders them.  (The full flat/hierarchical/ragged x chunks x wire
    matrix is the slow test below / `bench.py --profile`.)"""
    obs = tmp_path / "obs"
    records = run_ledger_matrix(str(obs), quick=True, steps=1,
                                overlapped=False, warn=False)
    assert len(records) == 1
    rec = records[0]
    assert set(rec["phases"]) == set(PHASES)
    assert all(v > 0 for v in rec["phases"].values())
    # fenced phases are disjoint sub-intervals of the profiled step
    assert sum(rec["phases"].values()) <= rec["step_ms"] * 1.05
    # artifacts: ledger rows for every phase + a valid Chrome trace
    rows = [json.loads(line) for line in
            (obs / "ledger.jsonl").read_text().splitlines()]
    assert {r["phase"] for r in rows if "phase" in r} == set(PHASES)
    doc = json.loads((obs / "trace.json").read_text())
    assert validate_trace(doc) == []
    assert (obs / "flight.jsonl").exists()

    from flashmoe_tpu import observe

    assert observe.main(["--ledger", str(obs / "ledger.jsonl")]) == 0
    led = observe.ledger_report(rows)
    assert led["n"] == len(PHASES)
    # both vocabularies ride every row: the matrix point name the docs
    # and bench records speak, and the planner path it joins against
    assert led["points"][0]["point"] == "flat"
    assert led["points"][0]["path"] == "collective"
    assert all(r["point"] == "flat" for r in rows if "phase" in r)


@pytest.mark.slow
def test_ledger_matrix_full_acceptance(devices, tmp_path):
    """Acceptance matrix: flat/hierarchical/ragged x {serial, chunked}
    x {wire off, e4m3} all measured, joined, and exported — with the
    measured-overlap cross-check against chunked_overlap_bound."""
    obs = tmp_path / "obs"
    records = run_ledger_matrix(str(obs), steps=1, overlapped=True,
                                warn=False)
    assert len(records) == 12  # 3 paths x 2 chunk settings x 2 wires
    for rec in records:
        assert set(rec["phases"]) == set(PHASES), rec["metric"]
        assert rec["overlap"] is not None  # every point is d > 1
    assert {r["path"] for r in records} == {"flat", "hierarchical",
                                            "ragged"}
    assert {r["a2a_chunks"] for r in records} == {1, 2}
    assert {r["wire_dtype"] for r in records} == {"off", "e4m3"}
    doc = json.loads((obs / "trace.json").read_text())
    assert validate_trace(doc) == []
    # one Perfetto process per matrix point
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert len(pids) == 12


# ----------------------------------------------------------------------
# Perfetto / Chrome-trace export
# ----------------------------------------------------------------------

def _toy_timeline():
    tl = PhaseTimeline(label="toy")
    tl.begin_step(0)
    for ph in ("moe.gate", "moe.expert.0", "moe.expert.1"):
        tok = tl.span_enter(ph)
        time.sleep(0.001)
        tl.span_exit(ph, tok)
    tl.end_step()
    with tl.section("train.checkpoint", step=0):
        pass
    tl.counter("moe.load_imbalance", 1.5, step=0)
    return tl


def test_trace_export_schema_and_content(tmp_path):
    tl = _toy_timeline()
    path = tmp_path / "trace.json"
    doc = write_trace(tl, str(path), labels=["point one"])
    assert validate_trace(doc) == []
    on_disk = json.loads(path.read_text())
    events = on_disk["traceEvents"]
    names = {e["name"] for e in events}
    assert {"process_name", "thread_name", "moe.gate",
            "train.checkpoint", "moe.load_imbalance"} <= names
    # chunked sub-slices carry their merged base phase in args
    sub = next(e for e in events if e["name"] == "moe.expert.1")
    assert sub["args"]["phase"] == "moe.expert"
    assert sub["tid"] == 0 and sub["dur"] > 0
    sec = next(e for e in events if e["name"] == "train.checkpoint")
    assert sec["tid"] == 1
    counter = next(e for e in events if e["ph"] == "C")
    assert counter["args"]["value"] == 1.5
    # multi-timeline merge: one pid per timeline
    doc2 = trace_document([tl, _toy_timeline()])
    assert {e["pid"] for e in doc2["traceEvents"]} == {0, 1}


def test_trace_validation_rejects_malformed():
    assert validate_trace({"nope": 1})
    assert validate_trace({"traceEvents": [{"ph": "Q", "name": "x",
                                            "pid": 0}]})
    bad_dur = {"traceEvents": [
        {"ph": "X", "name": "x", "pid": 0, "tid": 0, "ts": 1.0,
         "dur": 0}]}
    assert any("dur" in e for e in validate_trace(bad_dur))
    bad_ts = {"traceEvents": [
        {"ph": "X", "name": "x", "pid": 0, "tid": 0, "ts": -5,
         "dur": 1}]}
    assert any("ts" in e for e in validate_trace(bad_ts))
    bad_counter = {"traceEvents": [
        {"ph": "C", "name": "c", "pid": 0, "ts": 1.0,
         "args": {"value": "high"}}]}
    assert any("numeric" in e for e in validate_trace(bad_counter))


# ----------------------------------------------------------------------
# SLO watchdog
# ----------------------------------------------------------------------

def test_slo_config_validation():
    with pytest.raises(ValueError, match="step_ms"):
        SLOConfig(step_ms=-1.0)
    with pytest.raises(ValueError, match="consecutive"):
        SLOConfig(step_ms=1.0, consecutive=0)
    with pytest.raises(ValueError, match="unknown SLO keys"):
        SLOConfig.from_dict({"step_milliseconds": 5})
    cfg = SLOConfig.from_dict({"step_ms": 10, "phase_ms":
                               {"moe.expert": 5}})
    assert cfg.phase_budgets == {"moe.expert": 5.0}


def test_slo_yaml_sidecar_fallback_parser(tmp_path):
    text = """
# budgets for the nightly job
step_ms: 250
consecutive: 2
demote_backend: ragged
phase_ms:
  moe.expert: 120
  moe.a2a_dispatch: 40.5
"""
    raw = _parse_flat_yaml(text)
    assert raw["step_ms"] == 250
    assert raw["phase_ms"]["moe.a2a_dispatch"] == 40.5
    p = tmp_path / "slo.yaml"
    p.write_text(text)
    cfg = SLOConfig.from_yaml(str(p))
    assert cfg.step_ms == 250.0
    assert cfg.consecutive == 2
    assert cfg.demote_backend == "ragged"
    assert cfg.phase_budgets["moe.expert"] == 120.0


def test_slo_breach_recover_episodes_and_escalation():
    from flashmoe_tpu.planner.select import (
        failed_backends, reset_path_failures,
    )

    m = Metrics()
    wd = SLOWatchdog(SLOConfig(step_ms=10.0, consecutive=2,
                               demote_backend="fused",
                               phase_ms=(("moe.expert", 5.0),)), m)
    try:
        assert wd.observe_step(0, 3.0) == []          # in budget
        ev = wd.observe_step(1, 50.0,
                             phases={"moe.expert": 7.0})
        assert {e["target"] for e in ev} == {"step", "moe.expert"}
        assert wd.consecutive_breaches == 1
        assert "fused" not in failed_backends()       # not yet
        wd.observe_step(2, 50.0)
        assert wd.consecutive_breaches == 2
        # consecutive budget hit: escalated into path demotion, once
        assert "fused" in failed_backends()
        assert m.counters["slo.escalations"] == 1
        wd.observe_step(3, 60.0)
        assert m.counters["slo.escalations"] == 1     # same episode
        # recovery closes the episode (and the phase target separately)
        wd.observe_step(4, 2.0, phases={"moe.expert": 1.0})
        recs = [d for d in m.decisions
                if d["decision"] == "slo.recovered"]
        assert {r["target"] for r in recs} == {"step", "moe.expert"}
        assert wd.consecutive_breaches == 0
        breaches = [d for d in m.decisions
                    if d["decision"] == "slo.breach"]
        assert breaches[0]["measured_ms"] == 50.0
        assert breaches[0]["budget_ms"] == 10.0
    finally:
        reset_path_failures()


def test_slow_step_chaos_fault_trips_slo_and_demotes(devices, tmp_path):
    """The acceptance wiring: an injected slow_step chaos fault makes a
    step blow its SLO budget -> slo.breach -> report_path_failure
    demotes the named backend through the PR 3 machinery."""
    from flashmoe_tpu.chaos import FaultPlan, wrap_step
    from flashmoe_tpu.planner.select import (
        failed_backends, reset_path_failures,
    )
    from flashmoe_tpu.runtime.resilient import (
        ResilienceConfig, resilient_train,
    )
    from flashmoe_tpu.runtime.trainer import TrainState

    state = TrainState(params={"w": jnp.zeros((4,))},
                       opt_state={"m": jnp.zeros((4,))},
                       step=jnp.zeros((), jnp.int32))

    def step_fn(s, b):
        return (TrainState(s.params, s.opt_state, s.step + 1, s.guard),
                {"loss": jnp.float32(1.0)})

    wrapped = wrap_step(step_fn, FaultPlan("slow_step", step=1,
                                           sleep_s=0.3))
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=100)
    g0 = len(global_metrics.decisions)
    try:
        resilient_train(
            state, wrapped, iter(lambda: {"x": 0}, None), num_steps=4,
            rcfg=rcfg,
            slo=SLOConfig(step_ms=100.0, consecutive=1,
                          demote_backend="ragged"))
        breaches = [d for d in global_metrics.decisions[g0:]
                    if d["decision"] == "slo.breach"]
        assert len(breaches) >= 1
        assert breaches[0]["step"] == 1  # the stalled step
        assert "ragged" in failed_backends()
        fallbacks = [d for d in global_metrics.decisions[g0:]
                     if d["decision"] == "planner.fallback"]
        assert any(d.get("failed") == "ragged" for d in fallbacks)
    finally:
        reset_path_failures()


# ----------------------------------------------------------------------
# flight-ring offset export (the mode-"w" data-loss fix)
# ----------------------------------------------------------------------

def test_flight_offset_export_loses_nothing_across_ring_wrap(tmp_path):
    rec = FlightRecorder(capacity=4)
    path = str(tmp_path / "flight.jsonl")
    for i in range(3):
        rec.record(step=i)
    cursor = rec.export_jsonl(path, start=0)
    assert cursor == 3
    # four more records: the ring WRAPS (steps 0-2 rotate out), but they
    # were already flushed — the legacy mode-"w" snapshot would have
    # discarded them here
    for i in range(3, 7):
        rec.record(step=i)
    assert len(rec) == 4 and rec.total_recorded == 7
    cursor = rec.export_jsonl(path, start=cursor)
    assert cursor == 7
    steps = [json.loads(line)["step"]
             for line in open(path).read().splitlines()]
    # two exports across a wrap: every record exactly once, in order
    assert steps == list(range(7))


def test_flight_export_gap_is_counted_not_silent(tmp_path):
    rec = FlightRecorder(capacity=2)
    for i in range(5):
        rec.record(step=i)
    lost0 = global_metrics.counters.get("flight.export_lost", 0)
    cursor = rec.export_jsonl(str(tmp_path / "f.jsonl"), start=0)
    assert cursor == 5
    # steps 0-2 were never flushed and already rotated out: visible loss
    assert global_metrics.counters["flight.export_lost"] - lost0 == 3


def test_flight_snapshot_mode_unchanged(tmp_path):
    rec = FlightRecorder(capacity=4)
    for i in range(6):
        rec.record(step=i)
    path = str(tmp_path / "snap.jsonl")
    assert rec.export_jsonl(path) == 4
    assert rec.export_jsonl(path) == 4  # truncates, not appends
    steps = [json.loads(line)["step"]
             for line in open(path).read().splitlines()]
    assert steps == [2, 3, 4, 5]


def test_trainer_periodic_flush_survives_ring_wrap(devices, tmp_path):
    """runtime.trainer.train(flight_flush_every=n) with a ring smaller
    than the run: the flight JSONL still carries EVERY step."""
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.runtime.trainer import train

    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=32, num_layers=1,
                    moe_frequency=1, vocab_size=256, num_heads=2,
                    drop_tokens=False, is_training=True,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:1])

    def batches():
        i = 0
        while True:
            yield {"tokens": jax.random.randint(
                jax.random.PRNGKey(i), (1, 33), 0, 256)}
            i += 1

    path = tmp_path / "flight.jsonl"
    train(cfg, mesh, batches(), num_steps=5,
          recorder=FlightRecorder(capacity=2),
          flight_path=str(path), flight_flush_every=2)
    steps = [json.loads(line)["step"]
             for line in path.read_text().splitlines()]
    assert steps == [0, 1, 2, 3, 4]


# ----------------------------------------------------------------------
# crash postmortem bundles
# ----------------------------------------------------------------------

def test_postmortem_bundle_roundtrip(tmp_path):
    from flashmoe_tpu import observe
    from flashmoe_tpu.profiler import postmortem as pm

    m = Metrics()
    m.decision("planner.path_select", backend="ragged")
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=16)
    tl = _toy_timeline()
    try:
        raise RuntimeError("synthetic crash for the bundle test")
    except RuntimeError as e:
        bundle = pm.write_bundle(
            str(tmp_path / "pmd"), error=e, cfg=cfg, metrics_obj=m,
            history=[{"loss": 1.5}, {"loss": 1.25}], timeline=tl,
            step=7, extra={"retries": 3})
    assert bundle is not None and pm.is_bundle(bundle)
    assert pm.find_bundles(str(tmp_path / "pmd")) == [bundle]
    loaded = pm.load_bundle(bundle)
    assert "synthetic crash" in loaded["manifest"]["error"]
    assert loaded["manifest"]["step"] == 7
    assert loaded["config"]["num_experts"] == 4
    assert loaded["flight"][-1]["loss"] == 1.25
    assert "RuntimeError" in loaded["traceback"]
    assert validate_trace(loaded["trace"]) == []
    assert any(d["decision"] == "postmortem.saved"
               for d in loaded["decisions"])
    rep = observe.postmortem_report(loaded)
    assert rep["step"] == 7
    assert rep["last_losses"] == [1.5, 1.25]
    assert rep["config"]["num_experts"] == 4
    assert rep["extra"] == {"retries": 3}
    text = observe.render_postmortem_text(rep)
    assert "synthetic crash" in text
    # the CLI path
    assert observe.main(["--postmortem", str(tmp_path / "pmd")]) == 0
    assert observe.main(["--postmortem", str(tmp_path / "empty")]) == 2


def test_postmortem_written_when_chaos_fault_exhausts_retries(
        devices, tmp_path):
    """A chaos device_loss that outlives the retry budget kills the
    in-job recovery — the StepFailure must leave a parseable bundle
    behind (and carry its path on the exception)."""
    from flashmoe_tpu.chaos import FaultPlan, make_injector
    from flashmoe_tpu.profiler import postmortem as pm
    from flashmoe_tpu.runtime.resilient import (
        ResilienceConfig, StepFailure, resilient_train,
    )
    from flashmoe_tpu.runtime.trainer import TrainState

    state = TrainState(params={"w": jnp.zeros((4,))},
                       opt_state={"m": jnp.zeros((4,))},
                       step=jnp.zeros((), jnp.int32))

    def step_fn(s, b):
        return (TrainState(s.params, s.opt_state, s.step + 1, s.guard),
                {"loss": jnp.float32(1.0)})

    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=100, max_retries=1,
                            emergency_save=False)
    plan = FaultPlan("device_loss", step=1)
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=16)
    pm_dir = str(tmp_path / "postmortem")
    metrics = Metrics()
    with pytest.raises(StepFailure) as exc:
        resilient_train(state, step_fn, iter(lambda: {"x": 0}, None),
                        num_steps=4, rcfg=rcfg, metrics=metrics,
                        fail_injector=make_injector(plan, rcfg),
                        postmortem_dir=pm_dir, cfg=cfg)
    bundles = pm.find_bundles(pm_dir)
    assert len(bundles) == 1
    assert getattr(exc.value, "postmortem_bundle", None) == bundles[0]
    loaded = pm.load_bundle(bundles[0])
    assert "device loss" in loaded["manifest"]["error"]
    assert loaded["manifest"]["extra"]["num_steps"] == 4
    assert loaded["config"]["num_experts"] == 4
    assert any(d["decision"] == "postmortem.saved"
               for d in loaded["decisions"])


def test_no_postmortem_on_recovered_failure(devices, tmp_path):
    """A transient failure absorbed by restore-and-retry is NOT a death:
    the bundle dir must stay empty (the chaos matrix asserts this per
    fault; this is the unit-level version)."""
    from flashmoe_tpu.profiler import postmortem as pm
    from flashmoe_tpu.runtime.resilient import (
        ResilienceConfig, resilient_train,
    )
    from flashmoe_tpu.runtime.trainer import TrainState

    state = TrainState(params={"w": jnp.zeros((4,))},
                       opt_state={"m": jnp.zeros((4,))},
                       step=jnp.zeros((), jnp.int32))

    def step_fn(s, b):
        return (TrainState(s.params, s.opt_state, s.step + 1, s.guard),
                {"loss": jnp.float32(1.0)})

    fired = {"n": 0}

    def inject_once(i):
        if i == 1 and not fired["n"]:
            fired["n"] += 1
            raise RuntimeError("transient")

    pm_dir = str(tmp_path / "postmortem")
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2, max_retries=2)
    final, _ = resilient_train(
        state, step_fn, iter(lambda: {"x": 0}, None), num_steps=4,
        rcfg=rcfg, fail_injector=inject_once, postmortem_dir=pm_dir)
    assert int(final.step) == 4
    assert pm.find_bundles(pm_dir) == []
