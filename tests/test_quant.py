"""Quantized expert storage & compute (ISSUE 15, flashmoe_tpu/quant/).

The acceptance spine:

* codec properties (zero channels exact, scale invariance, symmetric
  int8, per-K-group scales);
* ``expert_quant=None`` traces the byte-identical graph (the invariant
  engine's matrix cell, run targeted here);
* the CI'd closeness gate — int8 per-channel MoE-layer output rel-err
  <= 2e-2 vs f32 on the REFERENCE config;
* fake-quant (full-precision params + knob) is BIT-identical to
  pre-quantized state execution on every XLA backend;
* the golden ``quant`` dimension: int8 cuts the modeled fused[rowwin]
  weight-stream time to <= 0.55x f32 on the mixtral point and closes
  the recorded rowwin-vs-collective margin;
* a 50-step quantized-serving drill producing finite, stop-token-
  terminating generations;
* storage: quantize/dequantize round trip, CRC'd metadata,
  measurement-identity separation, controller re-placement coherence.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu import quant as qt
from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _cfg(**over):
    base = dict(num_experts=8, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=256, ep=4,
                drop_tokens=False, **F32)
    base.update(over)
    return MoEConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    return cfg, params, x


# ----------------------------------------------------------------------
# Codec properties (quant/core.py)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("qname", ["int8", "e4m3"])
def test_codec_roundtrip_properties(qname):
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(4, 32, 64).astype(np.float32))
    payload, scales = qt.quantize_channelwise(w, qname)
    assert payload.shape == w.shape
    assert scales.shape == (4, 1, 64) and scales.dtype == jnp.float32
    # round-trip error well inside the layer gate's budget
    assert float(qt.roundtrip_error(w, qname)) < 0.05
    # zero channels survive exactly (scale pinned to 1.0)
    wz = w.at[:, :, 5].set(0.0)
    rt = qt.roundtrip(wz, qname)
    np.testing.assert_array_equal(np.asarray(rt[:, :, 5]), 0.0)
    # positive per-channel rescaling rescales the decode exactly
    c = jnp.asarray(rng.uniform(0.5, 4.0, (1, 1, 64)).astype(np.float32))
    base = np.asarray(qt.roundtrip(w, qname), np.float64)
    scaled = np.asarray(qt.roundtrip(w * c, qname), np.float64)
    np.testing.assert_allclose(scaled, base * np.asarray(c, np.float64),
                               rtol=1e-5, atol=1e-7)


def test_codec_int8_symmetric_and_grouped():
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(2, 64, 32).astype(np.float32))
    payload, _ = qt.quantize_channelwise(w, "int8")
    p = np.asarray(payload)
    assert p.dtype == np.int8 and p.min() >= -127 and p.max() <= 127
    # negation round-trips exactly through the symmetric grid
    pn, sn = qt.quantize_channelwise(-w, "int8")
    np.testing.assert_array_equal(np.asarray(pn), -p)
    # per-K-group scales: finer groups, lower error; shapes carry the
    # grouping so decode needs no side channel
    pg, sg = qt.quantize_channelwise(w, "int8", group_size=16)
    assert sg.shape == (2, 4, 32)
    err_g = float(qt.core.roundtrip_error(w, "int8", group_size=16))
    err_c = float(qt.roundtrip_error(w, "int8"))
    assert err_g <= err_c + 1e-9
    np.testing.assert_allclose(
        np.asarray(qt.dequantize_channelwise(pg, sg)),
        np.asarray(w), rtol=0.1, atol=0.05)
    with pytest.raises(ValueError, match="group_size"):
        qt.quantize_channelwise(w, "int8", group_size=7)
    with pytest.raises(ValueError, match="unknown expert_quant"):
        qt.quantize_channelwise(w, "int4")


def test_calibration_is_deterministic_and_never_worse():
    cfg = _cfg(gated_ffn=True, hidden_act="silu", ep=1)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    r1 = qt.calibrate(params, cfg, "int8")
    r2 = qt.calibrate(params, cfg, "int8")
    assert r1.percentile == r2.percentile
    assert r1.output_rel_err == r2.output_rel_err
    # absmax (p100) is always a candidate, so the winner can never be
    # worse than uncalibrated on the sample it measured
    assert r1.output_rel_err <= r1.report["p100"] + 1e-12
    qs = qt.quantize_state(params, "int8", calibration=r1)
    assert qt.is_quantized(qs.params)


# ----------------------------------------------------------------------
# Config surface
# ----------------------------------------------------------------------

def test_config_validation():
    _cfg(expert_quant="int8")           # canonical
    _cfg(expert_quant="fp8")            # alias of e4m3
    with pytest.raises(ValueError, match="unknown expert_quant"):
        _cfg(expert_quant="int4")
    with pytest.raises(ValueError, match="post-training"):
        _cfg(expert_quant="int8", is_training=True)
    with pytest.raises(ValueError, match="tp>1"):
        _cfg(expert_quant="int8", tp=2, moe_backend="collective")
    # fused composes (boundary dequant / rowwin in-VMEM dequant)
    _cfg(expert_quant="int8", moe_backend="fused")


def test_quantized_state_under_quant_off_config_refused(setup, devices):
    """Code-review guard: a quantized state reaching a quant-off
    config must raise at trace time — matmuling raw ±127 payloads with
    the scales silently ignored is finite garbage, not an error."""
    cfg, params, x = setup
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    qs = qt.quantize_state(params, "int8")
    with pytest.raises(ValueError, match="expert_quant is None"):
        jax.make_jaxpr(
            lambda p, xx: ep_moe_layer(p, xx, cfg, mesh).out)(
            qs.params, x)
    with pytest.raises(ValueError, match="expert_quant is None"):
        jax.make_jaxpr(
            lambda p, xx: moe_layer(p, xx, cfg.replace(ep=1),
                                    use_pallas=False).out)(qs.params, x)


def test_fused_path_rejects_per_group_scales(setup, devices):
    """Code-review guard: per-K-group scales would boundary-dequantize
    while the planner prices the per-channel int8 streamer — the fused
    layer refuses the divergence outright."""
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer

    cfg, params, x = setup
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    qs = qt.quantize_state(params, "int8", group_size=16)
    cq = cfg.replace(expert_quant="int8", fused_schedule="rowwin")
    with pytest.raises(ValueError, match="per-OUTPUT-CHANNEL"):
        jax.make_jaxpr(
            lambda p, xx: fused_ep_moe_layer(p, xx, cq, mesh).out)(
            qs.params, x)


def test_invariant_engine_covers_expert_quant(devices):
    """The registered KnobSpec: off = bit-identical everywhere, on adds
    int8 ops but never an exchange — run the engine's own matrix cell
    so a quant regression fails HERE, not just in the full staticcheck
    subprocess."""
    from flashmoe_tpu.staticcheck.invariants import run_invariants

    out = run_invariants(knobs=["expert_quant"], devices=devices)
    assert out == [], [str(v) for v in out]


# ----------------------------------------------------------------------
# Execution: closeness + fake-quant/pre-quant identity
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_reference_config_int8_closeness_gate():
    """THE acceptance numerics gate: int8 per-channel quantized
    MoE-layer output within 2e-2 relative error of the f32 layer on
    the reference config (E=64, H=2048, I=2048, S=8192)."""
    cfg = BENCH_CONFIGS["reference"].replace(ep=1, **F32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    base = moe_layer(params, x, cfg, use_pallas=False)
    qs = qt.quantize_state(params, "int8")
    qout = moe_layer(qs.params, x, cfg.replace(expert_quant="int8"),
                     use_pallas=False)
    num = jnp.linalg.norm((qout.out - base.out).astype(jnp.float32))
    den = jnp.linalg.norm(base.out.astype(jnp.float32))
    rel = float(num / den)
    assert rel <= 2e-2, f"int8 rel err {rel} exceeds the 2e-2 gate"
    # routing itself is untouched: the gate runs at full precision
    np.testing.assert_array_equal(np.asarray(qout.expert_counts),
                                  np.asarray(base.expert_counts))


@pytest.mark.slow
def test_fake_quant_bit_identical_to_prequantized_state(setup, devices):
    """cfg.expert_quant with full-precision params fake-quants in-graph
    with the SAME absmax arithmetic quantize_state bakes offline — the
    two arms must agree bit-for-bit on every XLA backend, so a numerics
    A/B needs no stored artifacts."""
    cfg, params, x = setup
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    qs = qt.quantize_state(params, "int8")
    cq = cfg.replace(expert_quant="int8")
    for layer, kw in ((ep_moe_layer, {}),
                      (ragged_ep_moe_layer, {"exchange": "dense"})):
        fake = layer(params, x, cq, mesh, **kw)
        pre = layer(qs.params, x, cq, mesh, **kw)
        np.testing.assert_array_equal(np.asarray(fake.out),
                                      np.asarray(pre.out))
    # and the quantized output stays close to full precision
    base = ep_moe_layer(params, x, cfg, mesh)
    fake = ep_moe_layer(params, x, cq, mesh)
    rel = float(jnp.linalg.norm(fake.out - base.out)
                / jnp.linalg.norm(base.out))
    assert 0 < rel <= 2e-2


@pytest.mark.slow
def test_quant_error_stat_rides_moestats(setup, devices):
    cfg, params, x = setup
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    cq = cfg.replace(expert_quant="int8", collect_stats=True)
    fake = ep_moe_layer(params, x, cq, mesh)
    # fake-quant reports the real round-trip loss...
    assert 0.0 < float(fake.stats.quant_error) < 0.05
    # ...a pre-quantized state short-circuits to 0 (its baked loss
    # lives in the state's metadata; re-measuring would pay full
    # weight passes to report ~0 — code-review finding)
    qs = qt.quantize_state(params, "int8")
    pre = ep_moe_layer(qs.params, x, cq, mesh)
    assert float(pre.stats.quant_error) == 0.0
    # off = field stays 0 and the stats tuple is unchanged otherwise
    off = ep_moe_layer(params, x, cfg.replace(collect_stats=True), mesh)
    assert float(off.stats.quant_error) == 0.0
    host = __import__("flashmoe_tpu.ops.stats",
                      fromlist=["stats_to_host"]).stats_to_host(
        fake.stats)
    assert "quant_error" in host


def test_dequantize_state_roundtrip_closeness():
    cfg = _cfg(gated_ffn=True, hidden_act="silu")
    params = init_moe_params(jax.random.PRNGKey(3), cfg)
    qs = qt.quantize_state(params, "int8")
    deq = qt.dequantize_state(qs.params)
    assert not qt.is_quantized(deq)
    for k in ("w_up", "w_gate", "w_down"):
        np.testing.assert_allclose(np.asarray(deq[k]),
                                   np.asarray(params[k]),
                                   rtol=0.2, atol=0.02)
    # biases and the router never quantize
    np.testing.assert_array_equal(np.asarray(qs.params["b_up"]),
                                  np.asarray(params["b_up"]))
    np.testing.assert_array_equal(np.asarray(qs.params["gate_w"]),
                                  np.asarray(params["gate_w"]))
    # metadata: derivable, CRC'd, tamper-evident
    meta = qt.quant_metadata(qs.params)
    assert meta["dtype"] == "int8" and qt.verify_quant_metadata(meta)
    bad = dict(meta, dtype="e4m3")
    assert not qt.verify_quant_metadata(bad)
    assert qt.quant_metadata(params) is None
    assert qt.quant_bytes_saved(qs.params) > 0


# ----------------------------------------------------------------------
# Fused path: geometry re-solve + in-VMEM dequant algebra
# ----------------------------------------------------------------------

def test_rowwin_geometry_resolves_at_quantized_width():
    """ISSUE 15 tentpole: `fused.schedule_table` / `_rowwin_tiles`
    re-solve tile geometry at the quantized bytes-per-element — the
    int8 store budgets its window double-buffer at 1 B/elem, so the
    IO-aware chooser takes a wider K-window (fewer HBM accumulator
    round-trips) on the mixtral shape."""
    from flashmoe_tpu.parallel.fused import schedule_table

    mix = BENCH_CONFIGS["mixtral"]
    off = schedule_table(mix, 8)
    on = schedule_table(mix.replace(expert_quant="int8"), 8)
    assert off["schedule"] == on["schedule"] == "rowwin"
    assert off["wdt"] == 2 and on["wdt"] == 1
    assert on["bi"] >= 2 * off["bi"]           # window doubles at 1 B
    assert on["n_i_chunks"] <= off["n_i_chunks"] // 2
    # off-path geometry is untouched by the knob's existence
    assert off == schedule_table(mix.replace(), 8)


def test_rowwin_in_vmem_dequant_algebra_emulation():
    """Kernel-free gate on the rowwin dequant algebra (this env's jax
    cannot launch the kernel — ROADMAP suite trajectory): emulate the
    window-major loop with int8 payload windows dequantized against
    per-output-channel scales in 'VMEM', and assert BIT equality with
    dequantize-then-stream (the boundary-dequant arm) plus closeness
    to the f32 chain."""
    rng = np.random.RandomState(0)
    cm, h, i, kw = 32, 64, 256, 64
    x = rng.randn(cm, h).astype(np.float32)
    wu = rng.randn(h, i).astype(np.float32)
    wd = rng.randn(i, h).astype(np.float32)
    pu, su = qt.quantize_channelwise(jnp.asarray(wu), "int8")
    pd, sd = qt.quantize_channelwise(jnp.asarray(wd), "int8")
    pu, su = np.asarray(pu), np.asarray(su)[0]          # [h,i], [i]
    pd, sd = np.asarray(pd), np.asarray(sd)[0]          # [i,h], [h]

    def relu(v):
        return np.maximum(v, 0.0)

    # boundary dequant: full matrices dequantized, then streamed
    wu_d = pu.astype(np.float32) * su[None, :]
    wd_d = pd.astype(np.float32) * sd[None, :]
    acc_boundary = np.zeros((cm, h), np.float32)
    for j in range(i // kw):
        hid = relu(x @ wu_d[:, j * kw:(j + 1) * kw])
        acc_boundary += hid @ wd_d[j * kw:(j + 1) * kw, :]

    # in-VMEM dequant: each int8 window dequantizes against its own
    # scale chunk (w_up's channels are the window's K columns; w_down's
    # are the full H row) — exactly the kernel's win_body arithmetic
    hbm = None
    for j in range(i // kw):
        acc = np.zeros((cm, h), np.float32) if j == 0 else hbm.copy()
        wu_win = pu[:, j * kw:(j + 1) * kw].astype(np.float32) \
            * su[None, j * kw:(j + 1) * kw]
        wd_win = pd[j * kw:(j + 1) * kw, :].astype(np.float32) \
            * sd[None, :]
        acc += relu(x @ wu_win) @ wd_win
        hbm = acc.astype(np.float32)
    np.testing.assert_array_equal(hbm, acc_boundary)
    dense = relu(x @ wu) @ wd
    rel = np.linalg.norm(hbm - dense) / np.linalg.norm(dense)
    assert rel < 2e-2


# ----------------------------------------------------------------------
# Pricing: analysis + planner + golden quant dimension
# ----------------------------------------------------------------------

def test_weight_stream_bytes_at_store_width():
    from flashmoe_tpu.analysis import (
        expert_weight_stream_bytes, path_costs,
    )

    mix = BENCH_CONFIGS["mixtral"]
    q = mix.replace(expert_quant="int8")
    off = expert_weight_stream_bytes(mix, 1)
    on = expert_weight_stream_bytes(q, 1)
    # bf16 -> int8 halves, plus the tiny f32 scale sidecar
    assert 0.50 <= on / off <= 0.51
    # honesty valve: an engine that boundary-dequantizes prices full
    assert expert_weight_stream_bytes(q, 1, quantized=False) == off
    # path_costs: the XLA paths and fused[rowwin] claim the discount,
    # the fused weights-once schedules do not
    for p in ("explicit", "ragged", "xla"):
        assert (path_costs(q, p, d_world=8).weight_bytes
                < path_costs(mix, p, d_world=8).weight_bytes)
    rw_on = path_costs(q, "fused", d_world=8, schedule="rowwin")
    rw_off = path_costs(mix, "fused", d_world=8, schedule="rowwin")
    assert rw_on.weight_bytes < 0.51 * rw_off.weight_bytes
    st_on = path_costs(q, "fused", d_world=8, schedule="stream")
    st_off = path_costs(mix, "fused", d_world=8, schedule="stream")
    assert st_on.weight_bytes == st_off.weight_bytes


def test_predictions_carry_quant_tag():
    from flashmoe_tpu.planner.model import predict_paths

    mix = BENCH_CONFIGS["mixtral"]
    qpreds = predict_paths(mix.replace(expert_quant="int8"), 8, "v5e")
    for p in qpreds:
        assert p.quant == "int8"
    for p in predict_paths(mix, 8, "v5e"):
        assert p.quant == "off"
    # the in-kernel combine has no quant arm (the layer forces the XLA
    # combine under expert_quant), so its row must be infeasible with
    # the reason — never a selected plan the engine silently downgrades
    fc = next(p for p in qpreds if p.path == "fused_combine")
    assert not fc.feasible and "no quant arm" in fc.note


def test_golden_quant_dimension_gates_rowwin_race():
    """THE headline golden gate (ISSUE 15 acceptance): on the mixtral
    point, int8 weights cut the modeled fused[rowwin] weight-stream
    time to <= 0.55x its full-precision value, and the recorded
    rowwin-vs-collective verdict re-derives under quant with a
    materially closed (or flipped) margin.  Checked against BOTH the
    committed table and a live recompute, so the table cannot go stale
    and the model cannot drift from the table."""
    from flashmoe_tpu.planner.golden import (
        GOLDEN_GENS, GOLDEN_QUANT, _quant_point, load_golden,
    )

    tbl = load_golden()
    assert set(GOLDEN_QUANT) == {"off", "int8"}
    mix = BENCH_CONFIGS["mixtral"]
    for gen in GOLDEN_GENS:
        stored = tbl["quant"]["mixtral"][gen]
        live = {q: _quant_point(mix.replace(**k), gen)
                for q, k in GOLDEN_QUANT.items()}
        for q in GOLDEN_QUANT:
            assert stored[q] == live[q], (gen, q)
        off, on = stored["off"], stored["int8"]
        assert on["rowwin_weight_ms"] <= 0.55 * off["rowwin_weight_ms"]
        # the race must close or flip — never widen
        assert (on["rowwin_beats_collective"]
                or on["rowwin_vs_collective"]
                < off["rowwin_vs_collective"])
    # every golden config carries the dimension (covered-dimension CI)
    for name in tbl["quant"]:
        for gen in GOLDEN_GENS:
            assert set(tbl["quant"][name][gen]) == set(GOLDEN_QUANT)


def test_measurement_identity_separates_quant():
    """A latency measured with int8 weights must never override a
    full-precision selection (and vice versa): tuning entries match the
    quant key strictly, and bench records carry expert_quant."""
    import os

    from flashmoe_tpu import tuning
    from flashmoe_tpu.planner.select import (
        _bench_record_latencies, _shape_key,
    )

    cfg = _cfg(ep=8)
    cq = cfg.replace(expert_quant="int8")
    assert _shape_key(cfg, 8)["quant"] == "off"
    assert _shape_key(cq, 8)["quant"] == "int8"

    entries = [
        {"kernel": "path_latency",
         "match": {"path": "collective", "h": 64, "quant": "int8"},
         "measured_ms": 1.5},
        {"kernel": "path_latency",
         "match": {"path": "ragged", "h": 64},
         "measured_ms": 2.5},
    ]
    assert tuning.validate_entries(
        {"generation": "test", "entries": entries}) == []
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as f:
        json.dump({"generation": "test", "entries": entries}, f)
        path = f.name
    os.environ["FLASHMOE_TUNING_FILE"] = path
    tuning._load.cache_clear()
    try:
        off = tuning.measured_path_latencies("test", h=64, quant="off")
        on = tuning.measured_path_latencies("test", h=64, quant="int8")
        assert off == {"ragged": 2.5}          # int8 entry filtered
        assert on == {"collective": 1.5}       # legacy entry filtered
    finally:
        os.environ.pop("FLASHMOE_TUNING_FILE", None)
        tuning._load.cache_clear()
        os.unlink(path)

    # bench records: the expert_quant field is part of the identity
    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        sig = (f"E={cfg.num_experts},k={cfg.expert_top_k},"
               f"H={cfg.hidden_size},I={cfg.intermediate_size},"
               f"S={cfg.tokens},float32")
        f.write(json.dumps({"metric": f"x[{sig}]", "path": "explicit",
                            "value": 3.0, "d": 8,
                            "expert_quant": "int8"}) + "\n")
        f.write(json.dumps({"metric": f"x[{sig}]", "path": "explicit",
                            "value": 4.0, "d": 8}) + "\n")
        rpath = f.name
    os.environ["FLASHMOE_BENCH_RECORDS"] = rpath
    try:
        assert _bench_record_latencies(cq, 8) == {"explicit": 3.0}
        assert _bench_record_latencies(cfg, 8) == {"explicit": 4.0}
    finally:
        os.environ.pop("FLASHMOE_BENCH_RECORDS", None)
        os.unlink(rpath)


def test_sentry_reference_points_cover_quant():
    from flashmoe_tpu.telemetry_plane.regression import reference_points

    pts = reference_points("v5e")
    assert "planner_predicted_ms[mixtral,d=8,v5e,quant=int8]" in pts
    assert "quant_rowwin_weight_ms[mixtral,d=8,v5e,quant=int8]" in pts


# ----------------------------------------------------------------------
# Controller re-placement coherence
# ----------------------------------------------------------------------

def test_permute_expert_state_moves_scales_with_payloads():
    """Satellite: the self-healing controller's replace path moves a
    quantized expert's payload AND scales together — decoding after the
    permutation must equal permuting the decoded weights."""
    from flashmoe_tpu.runtime.controller import permute_expert_state

    cfg = _cfg(ep=1)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    qs = qt.quantize_state(params, "int8")
    state = {"moe": dict(qs.params)}
    perm = (3, 0, 1, 2, 5, 4, 7, 6)
    moved = permute_expert_state(state, cfg, perm)["moe"]
    want = qt.dequantize_state(qs.params)
    got = qt.dequantize_state(moved)
    for k in ("w_up", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k])[np.asarray(perm)])
    # replica copy duplicates payload + scale coherently
    moved2 = permute_expert_state(state, cfg, tuple(range(8)),
                                  replica_pairs=((0, 7),))["moe"]
    got2 = qt.dequantize_state(moved2)
    np.testing.assert_array_equal(np.asarray(got2["w_up"][7]),
                                  np.asarray(want["w_up"][0]))


# ----------------------------------------------------------------------
# Serving: quantized engine drill + freed-HBM reporting
# ----------------------------------------------------------------------

def test_quantized_serving_drill_50_steps():
    """ISSUE 15 acceptance: a 50-step quantized-serving drill produces
    finite logits and stop-token-terminating generations, and the
    engine reports the freed weight HBM as extra KV-page headroom."""
    from flashmoe_tpu.models.generate import generate
    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.serving.engine import (
        Request, ServeConfig, ServingEngine,
    )
    from flashmoe_tpu.serving.loadgen import tiny_config
    from flashmoe_tpu.utils.telemetry import Metrics

    cfg = tiny_config().replace(expert_quant="int8")
    params = init_params(jax.random.PRNGKey(0), cfg.replace(
        expert_quant=None))
    qs = qt.quantize_state(params, "int8")
    prompts = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0,
                                 cfg.vocab_size)
    # pick per-request stop tokens from the quantized model's own
    # greedy continuations so at least one request stop-terminates
    probe = np.asarray(generate(qs.params, prompts[:1], cfg,
                                max_new_tokens=8))[0]
    stop = int(probe[-1])

    m = Metrics()
    eng = ServingEngine(qs, cfg,
                        ServeConfig(max_batch=4, page_size=8,
                                    num_pages=64, prompt_bucket=8),
                        metrics_obj=m)
    assert eng.quant_info is not None
    assert eng.quant_info["expert_quant"] == "int8"
    assert eng.quant_info["freed_bytes"] > 0
    assert eng.quant_info["extra_kv_pages"] >= 1
    reqs = [Request(rid=i, prompt=tuple(int(t) for t in prompts[i]),
                    max_new_tokens=50,
                    stop_tokens=(stop,) if i == 0 else ())
            for i in range(4)]
    out = eng.run(reqs)                 # {rid: prompt + generated}
    assert eng.summary()["completed"] == 4
    plen = prompts.shape[1]
    for i in range(4):
        toks = np.asarray(out[i])
        assert toks.size > plen
        assert np.all(toks >= 0) and np.all(toks < cfg.vocab_size)
    # request 0 terminated on its stop token, before its 50-step budget
    assert int(out[0][-1]) == stop
    assert len(out[0]) <= plen + 8
    # the others ran their full 50 decode steps
    assert len(out[1]) == plen + 50
    # engine outputs bit-equal to one-at-a-time generate() on the
    # quantized model (the PR 10 contract holds under quant)
    for i in range(1, 4):
        want = np.asarray(generate(qs.params, prompts[i:i + 1], cfg,
                                   max_new_tokens=50))[0]
        np.testing.assert_array_equal(np.asarray(out[i]), want)
    # summary + decision expose the freed HBM as KV-page headroom
    s = eng.summary()
    assert s["expert_quant"] == "int8"
    assert s["quant_extra_kv_pages"] == eng.quant_info["extra_kv_pages"]
    decs = [d for d in m.decisions if d.get("decision") == "serve.quant"]
    assert decs and decs[0]["extra_kv_pages"] >= 1
    # a FULL-precision checkpoint under the quant knob quantizes ONCE
    # at load (never fake-quants inside the jitted steps) and reports
    # the same freed HBM (code-review finding)
    eng2 = ServingEngine(params, cfg,
                         ServeConfig(max_batch=4, page_size=8,
                                     num_pages=64, prompt_bucket=8),
                         metrics_obj=Metrics())
    assert eng2.quant_info is not None
    assert qt.is_quantized(eng2.params)
    assert (eng2.quant_info["freed_bytes"]
            == eng.quant_info["freed_bytes"])


def test_observe_reports_quant():
    from flashmoe_tpu.observe import (
        quant_report, render_serving_text, serving_report,
    )

    flight = [{"step": 0, "moe": [{"quant_error": 0.004},
                                  {"quant_error": 0.006}]}]
    rep = quant_report(flight)
    assert rep["steps_with_quant"] == 2
    assert rep["max_quant_error"] == 0.006
    srep = serving_report([
        {"decision": "serve.quant", "expert_quant": "int8",
         "freed_mb": 1.5, "extra_kv_pages": 3, "num_pages": 32},
        {"kind": "serve_step", "tokens": 4, "step_ms": 1.0},
    ])
    assert srep["quant"]["extra_kv_pages"] == 3
    txt = render_serving_text(srep)
    assert "+3 KV pages" in txt


# ----------------------------------------------------------------------
# Checkpoint: quant block + back-compat (satellite; more in
# tests/test_checkpoint.py)
# ----------------------------------------------------------------------

def test_quant_metadata_block_crc():
    from flashmoe_tpu.quant import verify_quant_metadata

    cfg = _cfg(ep=1)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    meta = qt.quant_metadata(qt.quantize_state(params, "e4m3").params)
    assert meta["dtype"] == "e4m3"
    assert verify_quant_metadata(meta)
    assert verify_quant_metadata(None)          # legacy manifests pass
    assert not verify_quant_metadata({"dtype": "e4m3"})  # no CRC
