"""Dropless ragged grouping: plan invariants + layer-vs-oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import Activation, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops import ragged as rag
from flashmoe_tpu.ops.moe import moe_layer

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, drop_tokens=False)


def test_plan_positions_disjoint_and_segmented():
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    sequence_len=128, **F32)
    idx = jax.random.randint(jax.random.PRNGKey(0), (128, 2), 0, 4)
    bm = 16
    plan = rag.make_ragged_plan(idx, cfg, bm)
    pos = np.asarray(plan.position).reshape(-1)
    assert len(np.unique(pos)) == pos.size  # no collisions
    # every position sits inside its expert's padded segment
    counts = np.asarray(plan.counts)
    padded = ((counts + bm - 1) // bm) * bm
    starts = np.concatenate([[0], np.cumsum(padded)[:-1]])
    flat_e = np.asarray(idx).reshape(-1)  # s-major, matching position [S, K]
    for p, e in zip(pos, flat_e):
        assert starts[e] <= p < starts[e] + counts[e]
    # tile gids cover segments in order
    tg = np.asarray(plan.tile_gid)
    for e in range(4):
        t0 = starts[e] // bm
        for t in range(t0, (starts[e] + counts[e] + bm - 1) // bm):
            assert tg[t] == e


def test_roundtrip_identity():
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    sequence_len=128, **F32)
    idx = jax.random.randint(jax.random.PRNGKey(0), (128, 2), 0, 4)
    idx = idx.at[:, 1].set((idx[:, 0] + 1) % 4)
    x = jax.random.normal(jax.random.PRNGKey(1), (128, 64), jnp.float32)
    plan = rag.make_ragged_plan(idx, cfg, 16)
    buf = rag.ragged_dispatch(x, plan, cfg, 16)
    w = jnp.full((128, 2), 0.5, jnp.float32)
    out = rag.ragged_combine(buf, plan, w, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


@pytest.mark.parametrize("cfg", [
    MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
              intermediate_size=256, sequence_len=256, **F32),
    MoEConfig(num_experts=4, expert_top_k=3, hidden_size=128,
              intermediate_size=256, sequence_len=128, gated_ffn=True,
              hidden_act=Activation.SILU, **F32),
], ids=["top2", "gated_top3"])
def test_dropless_layer_matches_oracle(cfg):
    key = jax.random.PRNGKey(0)
    params = init_moe_params(key, cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    want, _ = reference_moe(params, x, cfg)
    got = moe_layer(params, x, cfg, use_pallas=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_skewed_routing_all_to_one():
    """Everything routed to one expert — ragged path must still be exact."""
    cfg = MoEConfig(num_experts=8, expert_top_k=1, hidden_size=64,
                    intermediate_size=128, sequence_len=128, **F32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    # strictly positive inputs + a ones-column gate make expert 3's logit
    # positive while all others stay 0 -> expert 3 wins every token
    params["gate_w"] = jnp.zeros_like(params["gate_w"]).at[:, 3].set(1.0)
    x = jnp.abs(
        jax.random.normal(jax.random.PRNGKey(1), (128, 64), jnp.float32)
    ) + 0.1
    want, _ = reference_moe(params, x, cfg)
    got = moe_layer(params, x, cfg, use_pallas=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    assert int(got.expert_counts[3]) == 128
