"""Distributed dropless (ragged all-to-all) EP layer vs the oracle.

XLA:CPU lacks the ragged-all-to-all op, so these tests run the dense-padded
exchange fallback — the layout/permutation logic (the hard part) is shared
between both exchange backends.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, drop_tokens=False)


def _setup(cfg, seed=0):
    pk, xk = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(pk, cfg)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), jnp.float32)
    return params, x


@pytest.mark.parametrize("ep", [2, 4, 8])
@pytest.mark.slow
def test_matches_oracle(ep, devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=ep, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:ep])
    out = ragged_ep_moe_layer(params, x, cfg, mesh, exchange="dense")
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    assert int(jnp.sum(out.expert_counts)) == cfg.tokens * cfg.expert_top_k


@pytest.mark.slow
def test_skewed_all_to_one_expert(devices):
    """Extreme imbalance: all tokens to one expert on one rank — the exact
    case capacity-based EP drops and dropless must not."""
    cfg = MoEConfig(num_experts=8, expert_top_k=1, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=4, **F32)
    params, x = _setup(cfg)
    params["gate_w"] = jnp.zeros_like(params["gate_w"]).at[:, 5].set(1.0)
    x = jnp.abs(x) + 0.1
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    out = ragged_ep_moe_layer(params, x, cfg, mesh, exchange="dense")
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
    assert int(out.expert_counts[5]) == cfg.tokens


@pytest.mark.slow
def test_gated_ffn(devices):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=128, ep=4,
                    gated_ffn=True, hidden_act="silu", **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    out = ragged_ep_moe_layer(params, x, cfg, mesh, exchange="dense")
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_sentinel_no_collision_with_padded_targets(devices):
    """Regression: tile padding can push a real row's target to exactly
    recv_bound; the dropped-row sentinel must be out of range, not
    recv_bound, or the scatter zeroes a real token."""
    cfg = MoEConfig(num_experts=4, expert_top_k=1, hidden_size=64,
                    intermediate_size=128, sequence_len=128, ep=2, **F32)
    params, x = _setup(cfg, seed=3)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    # try several routings; with block_m=16 the padded segments force the
    # collision case the review repro found
    for seed in range(3):
        xs = jax.random.normal(
            jax.random.PRNGKey(100 + seed), (cfg.tokens, 64), jnp.float32
        )
        out = ragged_ep_moe_layer(params, xs, cfg, mesh, exchange="dense",
                                  block_m=16)
        want, _ = reference_moe(params, xs, cfg)
        np.testing.assert_allclose(
            np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
        )


@pytest.mark.slow
def test_token_count_not_multiple_of_block(devices):
    """Regression: recv_bound not divisible by block_m must not crash."""
    cfg = MoEConfig(num_experts=4, expert_top_k=1, hidden_size=64,
                    intermediate_size=128, sequence_len=72, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    out = ragged_ep_moe_layer(params, x, cfg, mesh, exchange="dense",
                              block_m=16)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_pallas_path_gradients_match_xla_path(devices):
    """The dropless pallas path must differentiate (grouped_ffn_ad) and
    agree with the XLA-fallback path's gradients."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                    intermediate_size=128, sequence_len=256, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])

    def loss(p, use_pallas):
        o = ragged_ep_moe_layer(p, x, cfg, mesh, use_pallas=use_pallas,
                                interpret=use_pallas, exchange="dense")
        return (o.out.astype(jnp.float32) ** 2).sum()

    gp = jax.grad(lambda p: loss(p, True))(params)
    gx = jax.grad(lambda p: loss(p, False))(params)
    for k in gx:
        np.testing.assert_allclose(
            np.asarray(gp[k]), np.asarray(gx[k]),
            rtol=5e-3, atol=5e-3, err_msg=k,
        )


def test_pallas_grouped_ffn_path(devices):
    """The grouped Pallas kernel runs on the regrouped ragged buffer."""
    cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=128, ep=2, **F32)
    params, x = _setup(cfg)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    out = ragged_ep_moe_layer(params, x, cfg, mesh, exchange="dense",
                              use_pallas=True, interpret=True, block_m=16)
    want, _ = reference_moe(params, x, cfg)
    np.testing.assert_allclose(
        np.asarray(out.out), np.asarray(want), rtol=2e-4, atol=2e-4
    )
