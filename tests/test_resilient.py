"""Failure injection: detection, checkpoint restore, retry budget."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime.resilient import (
    ResilienceConfig, StepFailure, resilient_train,
)
from flashmoe_tpu.runtime.trainer import (
    init_state, make_optimizer, make_train_step, state_shardings,
)
from flashmoe_tpu.utils.telemetry import Metrics

CFG = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=32, num_layers=1,
                moe_frequency=1, vocab_size=256, num_heads=2,
                drop_tokens=False, is_training=True, ep=4,
                dtype=jnp.float32, param_dtype=jnp.float32)


def _fixture(devices):
    mesh = make_mesh(CFG)
    opt = make_optimizer(CFG, total_steps=8)
    state = init_state(jax.random.PRNGKey(0), CFG, opt)
    state = jax.device_put(state, state_shardings(state, CFG, mesh))
    step = make_train_step(CFG, mesh, opt)

    def batches():
        k = itertools.count()
        while True:
            yield {"tokens": jax.random.randint(
                jax.random.PRNGKey(next(k)), (2, 33), 0, 256)}

    return state, step, batches()


def test_recovers_from_transient_failure(devices, tmp_path):
    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=2, max_retries=3)
    metrics = Metrics()
    crashed = {"done": False}

    def injector(i):
        if i == 3 and not crashed["done"]:
            crashed["done"] = True
            raise RuntimeError("injected device loss")

    final, hist = resilient_train(state, step, data, num_steps=6,
                                  rcfg=rcfg, metrics=metrics,
                                  fail_injector=injector)
    assert int(final.step) == 6
    assert metrics.counters["failures"] == 1
    assert metrics.counters["restores"] == 1
    # steps after restore re-run from the checkpoint at step 2
    assert len(hist) >= 6
    assert all(np.isfinite(h["loss"]) for h in hist)


def test_nan_loss_recovers_before_first_checkpoint(devices, tmp_path):
    """A non-finite loss is a StepFailure — it must go through restore-and-
    retry, not re-raise (advisor finding, round 1).  The failure lands
    before any checkpoint exists AND after the jitted step donated the
    input state, so recovery must come from the undonated in-memory copy."""
    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck4"),
                            checkpoint_every=100, max_retries=2)
    metrics = Metrics()
    calls = {"n": 0}

    def nan_once_step(s, b):
        ns, m = step(s, b)
        calls["n"] += 1
        if calls["n"] == 1:
            m = dict(m, loss=jnp.float32("nan"))
        return ns, m

    final, hist = resilient_train(state, nan_once_step, data, num_steps=3,
                                  rcfg=rcfg, metrics=metrics)
    assert int(final.step) == 3
    assert metrics.counters["failures"] == 1
    assert metrics.counters["restores"] == 1
    assert all(np.isfinite(h["loss"]) for h in hist)


def test_retry_budget_exhausted(devices, tmp_path):
    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck2"),
                            checkpoint_every=2, max_retries=2)

    def always_fail(i):
        if i == 1:
            raise RuntimeError("permanent fault")

    with pytest.raises(StepFailure, match="failed 3 times"):
        resilient_train(state, step, data, num_steps=4, rcfg=rcfg,
                        fail_injector=always_fail)


def test_elastic_resume_smaller_world(devices, tmp_path):
    """World-size change: train on an ep=4 mesh, then resume on HALF the
    devices — the checkpoint reshards into the new mesh and training
    continues (the elasticity the reference's stalled collectives can
    never provide)."""
    from flashmoe_tpu.runtime.elastic import elastic_resume
    from flashmoe_tpu.runtime import checkpoint as ckpt

    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck_el"),
                            checkpoint_every=2)
    mid, _ = resilient_train(state, step, data, num_steps=2, rcfg=rcfg)
    assert ckpt.latest_step(rcfg.checkpoint_dir) == 2

    # "restart" on 4 devices: ep folds 4 -> 2 (divides E=4), dp absorbs
    new_state, new_mesh, new_cfg, opt = elastic_resume(
        CFG, rcfg.checkpoint_dir, devices=devices[:4])
    assert int(new_state.step) == 2
    assert dict(new_mesh.shape)["ep"] * dict(new_mesh.shape)["dp"] == 4
    step2 = make_train_step(new_cfg, new_mesh, opt)
    batch = {"tokens": jax.random.randint(
        jax.random.PRNGKey(99), (2, 33), 0, 256)}
    out_state, m = step2(new_state, batch)
    assert int(out_state.step) == 3
    assert np.isfinite(float(m["loss"]))


def test_resumes_from_existing_checkpoint(devices, tmp_path):
    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck3"),
                            checkpoint_every=2)
    mid, _ = resilient_train(state, step, data, num_steps=4, rcfg=rcfg)
    assert int(mid.step) == 4
    # a "fresh process": new step-0 state (the original was donated by the
    # jitted step), resumes at 4 from the shared checkpoint dir
    state2, step2, data2 = _fixture(devices)
    metrics = Metrics()
    final, hist = resilient_train(state2, step2, data2, num_steps=6,
                                  rcfg=rcfg, metrics=metrics)
    assert int(final.step) == 6
    assert metrics.counters["resumes"] == 1
    assert len(hist) == 2  # only steps 4 and 5 ran


def test_deadline_executor_reused_across_steps(devices, tmp_path,
                                               monkeypatch):
    """Satellite: one deadline executor serves the whole run — the old
    executor-per-step spawned (and leaked) a thread per step.  A new
    executor appears only after a timeout abandons the stuck one."""
    import flashmoe_tpu.runtime.resilient as res

    created = {"n": 0}
    real = res._make_deadline_executor

    def counting_executor():
        created["n"] += 1
        return real()

    monkeypatch.setattr(res, "_make_deadline_executor", counting_executor)
    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck"),
                            checkpoint_every=100, step_timeout_s=60.0)
    final, hist = resilient_train(state, step, data, num_steps=6,
                                  rcfg=rcfg)
    assert int(final.step) == 6
    assert created["n"] == 1  # six steps, ONE executor

    # a timeout abandons the stuck executor and the next step gets a
    # fresh one — stalls must not poison the deadline machinery.
    # Warm the compile OUTSIDE the deadline so it only races the stall.
    import time as _time
    state2, _step2, data2 = _fixture(devices)
    mesh = make_mesh(CFG)
    opt = make_optimizer(CFG, total_steps=8)
    warm = init_state(jax.random.PRNGKey(5), CFG, opt)
    warm = jax.device_put(warm, state_shardings(warm, CFG, mesh))
    jax.block_until_ready(step(warm, next(data2)))
    stall = {"left": 1}

    def stalling_step(s, b):
        if stall["left"]:
            stall["left"] -= 1
            _time.sleep(2.5)
        return step(s, b)

    created["n"] = 0
    rcfg2 = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck2"),
                             checkpoint_every=100, step_timeout_s=1.0,
                             max_retries=3)
    final2, _ = resilient_train(state2, stalling_step, data2, num_steps=3,
                                rcfg=rcfg2)
    assert int(final2.step) == 3
    assert created["n"] == 2  # one for the run + one after the timeout


def test_fold_parallelism_warns_on_dropped_axes():
    """Folding a pipelined/tensor-parallel config to dp x ep changes the
    execution strategy; it must say so instead of silently reshaping the
    job (VERDICT r3 weak #8)."""
    from flashmoe_tpu.runtime.elastic import fold_parallelism

    cfg = CFG.replace(ep=2, pp=2, tp=1, sp=1)
    with pytest.warns(UserWarning, match="dropping pp=2"):
        folded = fold_parallelism(cfg, 4)
    assert folded.pp == folded.tp == folded.sp == 1
    assert folded.ep * folded.dp == 4

    # a pure dp x ep config folds silently
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        fold_parallelism(CFG, 4)


def test_resilient_train_live_plane_healthz(devices, tmp_path):
    """`resilient_train(telemetry_port=0)` serves /healthz with step
    progress and the last DURABLE checkpoint step while the loop runs,
    and tears the thread down on exit (PR 13 live plane)."""
    import json as _json
    import urllib.request

    state, step, data = _fixture(devices)
    rcfg = ResilienceConfig(checkpoint_dir=str(tmp_path / "ck_tp"),
                            checkpoint_every=2)
    metrics = Metrics()
    seen = {}
    real_injector_calls = {"n": 0}

    def probing_injector(i):
        # piggyback on the per-step hook to scrape mid-run: the server
        # must answer while training is in flight
        real_injector_calls["n"] += 1
        if i == 3 and "hz" not in seen:
            start = metrics.last_decision("telemetry.server_start")
            url = f"http://127.0.0.1:{start['port']}/healthz"
            with urllib.request.urlopen(url, timeout=5) as r:
                seen["hz"] = _json.loads(r.read().decode())

    final, _ = resilient_train(state, step, data, num_steps=4,
                               rcfg=rcfg, metrics=metrics,
                               fail_injector=probing_injector,
                               telemetry_port=0)
    assert int(final.step) == 4
    hz = seen["hz"]
    assert hz["ok"] is True and hz["phase"] == "train"
    assert hz["step"] == 3 and hz["num_steps"] == 4
    assert hz["last_checkpoint_step"] == 2   # durable boundary at 2
    names = [d["decision"] for d in metrics.decisions]
    assert names.count("telemetry.server_start") == 1
    assert names.count("telemetry.server_stop") == 1
