"""Runtime layer: bootstrap, worker CLI, API facade."""

import json
import subprocess
import sys

import jax
import pytest
import jax.numpy as jnp

import flashmoe_tpu as fm
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.runtime import bootstrap


def setup_function(_):
    bootstrap.finalize()


def test_initialize_builds_runtime(devices):
    rt = bootstrap.initialize(MoEConfig(
        num_experts=8, hidden_size=128, intermediate_size=256,
        sequence_len=128,
    ))
    assert rt.cfg.ep == 8  # folded to available devices
    assert dict(rt.mesh.shape)["ep"] == 8
    assert rt.num_local_experts == 1
    assert bootstrap.get_runtime() is rt
    # idempotent
    assert bootstrap.initialize() is rt
    bootstrap.finalize()


def test_initialize_from_reference_json(devices, tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({
        "num_experts": 4, "expert_top_k": 2, "hidden_size": 128,
        "intermediate_size": 256, "sequence_len": 128, "torch_dtype": 0,
        "hidden_act": 1,
    }))
    rt = bootstrap.initialize(str(p))
    assert rt.cfg.num_experts == 4
    assert rt.cfg.ep == 4
    bootstrap.finalize()


def test_api_facade(devices):
    cc = fm.get_compiled_config()
    assert "num_experts" in cc and "hidden_size" in cc
    bootstrap.initialize(MoEConfig(num_experts=8, hidden_size=128,
                                   intermediate_size=256))
    assert fm.get_num_local_experts() >= 1
    bootstrap.finalize()


def test_bookkeeping_and_topo_export(devices, tmp_path):
    import flashmoe_tpu as fm
    from flashmoe_tpu.parallel.topology import ici_adjacency

    bootstrap.initialize(MoEConfig(num_experts=8, hidden_size=128,
                                   intermediate_size=256))
    bk = fm.get_bookkeeping()
    assert bk["mesh"]["ep"] == 8
    assert sorted(e for v in bk["local_experts"].values() for e in v) == \
        list(range(8))
    adj = ici_adjacency()
    p = tmp_path / "adj.txt"
    adj.export(str(p))
    text = p.read_text()
    assert "alpha" in text and "beta" in text
    bootstrap.finalize()


@pytest.mark.slow
def test_multiprocess_launcher(devices, tmp_path):
    """Two real processes form a jax.distributed cluster through the
    launcher + bootstrap env protocol (the nvshmrun-equivalent path) and
    run the MoE worker end-to-end."""
    import os
    from flashmoe_tpu.runtime.launcher import run_workers

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "num_experts": 2, "expert_top_k": 1, "hidden_size": 128,
        "intermediate_size": 256, "sequence_len": 128,
    }))
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",  # 1 CPU device per process -> 2 global
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = run_workers(2, config_path=str(cfg),
                         coordinator="127.0.0.1:9917")
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0


def test_slow_worker_shifts_placement(devices, tmp_path):
    """Measured placement end to end: two real processes run the full
    bootstrap (throughput probe + pairwise DCN probe + Decider); rank 1's
    measured rate is scaled down 8x and per-device memory is capped so the
    two workers must form one EP group — the Decider's rate-proportional
    assignment must then give the slow worker visibly fewer experts
    (reference: ``mT`` -> ``WorkerAttribute`` -> ``assign``,
    ``throughput.cuh:99-170``, ``decider.cuh:273-329``)."""
    import os
    from flashmoe_tpu.runtime.launcher import run_workers

    out = tmp_path / "placement"
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",  # 1 CPU device per process -> 2 global
        # each worker holds 3MB; 8 experts x 0.52MB need ~4.2MB -> a single
        # worker is infeasible, the pair must merge into one EP group
        "FLASHMOE_MEMORY_GB": "0.003",
        "FLASHMOE_PLACEMENT_OUT": str(out),
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = run_workers(
            2, coordinator="127.0.0.1:9919",
            per_rank_env={1: {"FLASHMOE_THROUGHPUT_SCALE": "0.125"}},
            worker_module="tests._placement_worker",
        )
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0
    rec = json.loads((tmp_path / "placement.rank0.json").read_text())
    counts = {int(k): v for k, v in rec["counts"].items()}
    assert rec["groups"] == [[0, 1]], rec  # memory forced one EP group
    assert counts[0] + counts[1] == 8
    assert counts[0] > counts[1], (
        f"slow worker should hold fewer experts: {counts}"
    )


def test_worker_cli(devices):
    """The worker runs end-to-end as a subprocess (reference worker.py)."""
    import os
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [sys.executable, "-m", "flashmoe_tpu.runtime.worker"],
        capture_output=True, text=True, env=env, timeout=300,
        cwd=__import__("pathlib").Path(__file__).parent.parent,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["finite"] is True
    assert rec["rank"] == 0


def test_heterogeneous_src_order_published():
    """bootstrap computes the fused kernel's arrival-order schedule from
    the adjacency: homogeneous -> None (ring default); a DCN-slowed rank
    -> an own-first order that sinks the slow source to the back."""
    import numpy as np

    from flashmoe_tpu.config import MoEConfig
    from flashmoe_tpu.parallel.topology import Adjacency
    from flashmoe_tpu.runtime.bootstrap import _heterogeneous_src_order

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=256, ep=4,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    alpha = np.full((4, 4), 0.001); np.fill_diagonal(alpha, 0.0)
    beta = np.full((4, 4), 0.02); np.fill_diagonal(beta, 0.0)
    assert _heterogeneous_src_order(Adjacency(alpha, beta), cfg, 4) is None

    a2, b2 = alpha.copy(), beta.copy()
    a2[3, :3] *= 20.0; b2[3, :3] *= 20.0
    order = _heterogeneous_src_order(Adjacency(a2, b2), cfg, 4)
    assert order is not None
    for r in range(3):
        assert order[r, 0] == r and order[r, -1] == 3  # slow source last
        assert sorted(order[r]) == [0, 1, 2, 3]

    # ep != n (e.g. dp x ep job): no table, ring default
    assert _heterogeneous_src_order(Adjacency(a2, b2),
                                    cfg.replace(ep=2), 4) is None


@pytest.mark.slow
def test_fused_layer_picks_up_runtime_src_order(monkeypatch, devices):
    """fused_ep_moe_layer adopts the bootstrapped table only when the
    mesh's device ordering matches its rank indexing.  Proof of
    consumption: a deliberately INVALID published table must surface as
    the launcher's own-first validation error — which can only happen if
    the pickup path actually read it."""
    import numpy as np
    import pytest as _pytest

    from flashmoe_tpu.config import MoEConfig
    from flashmoe_tpu.models.reference import init_moe_params, reference_moe
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.runtime import bootstrap as bs

    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                    intermediate_size=256, sequence_len=128, ep=4,
                    drop_tokens=False, dtype=jnp.float32,
                    param_dtype=jnp.float32)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    want, _ = reference_moe(params, x, cfg)

    class FakeRT:
        src_order = None

    monkeypatch.setattr(bs, "_runtime", FakeRT)

    # invalid published table -> ValueError proves the pickup read it
    FakeRT.src_order = np.array(
        [[1, 0, 2, 3]] * 4, np.int32)  # not own-first
    with _pytest.raises(ValueError, match="starting with"):
        fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)

    # valid reverse-ring table -> consumed, numerics still match oracle
    FakeRT.src_order = np.stack([
        np.array([r] + [(r - s) % 4 for s in range(1, 4)], np.int32)
        for r in range(4)
    ])
    out = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    np.testing.assert_allclose(np.asarray(out.out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)

    # mesh whose ep devices are NOT jax.devices() order: table refused,
    # ring default used (runs fine even though the table is garbage for
    # this mesh)
    perm = [devices[2], devices[0], devices[3], devices[1]]
    mesh_p = make_mesh(cfg, dp=1, devices=perm)
    FakeRT.src_order = np.array([[1, 0, 2, 3]] * 4, np.int32)  # invalid
    out_p = fused_ep_moe_layer(params, x, cfg, mesh_p, interpret=True)
    assert bool(jnp.isfinite(out_p.out).all())
