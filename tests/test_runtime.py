"""Runtime layer: bootstrap, worker CLI, API facade."""

import json
import subprocess
import sys

import jax

import flashmoe_tpu as fm
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.runtime import bootstrap


def setup_function(_):
    bootstrap.finalize()


def test_initialize_builds_runtime(devices):
    rt = bootstrap.initialize(MoEConfig(
        num_experts=8, hidden_size=128, intermediate_size=256,
        sequence_len=128,
    ))
    assert rt.cfg.ep == 8  # folded to available devices
    assert dict(rt.mesh.shape)["ep"] == 8
    assert rt.num_local_experts == 1
    assert bootstrap.get_runtime() is rt
    # idempotent
    assert bootstrap.initialize() is rt
    bootstrap.finalize()


def test_initialize_from_reference_json(devices, tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({
        "num_experts": 4, "expert_top_k": 2, "hidden_size": 128,
        "intermediate_size": 256, "sequence_len": 128, "torch_dtype": 0,
        "hidden_act": 1,
    }))
    rt = bootstrap.initialize(str(p))
    assert rt.cfg.num_experts == 4
    assert rt.cfg.ep == 4
    bootstrap.finalize()


def test_api_facade(devices):
    cc = fm.get_compiled_config()
    assert "num_experts" in cc and "hidden_size" in cc
    bootstrap.initialize(MoEConfig(num_experts=8, hidden_size=128,
                                   intermediate_size=256))
    assert fm.get_num_local_experts() >= 1
    bootstrap.finalize()


def test_bookkeeping_and_topo_export(devices, tmp_path):
    import flashmoe_tpu as fm
    from flashmoe_tpu.parallel.topology import ici_adjacency

    bootstrap.initialize(MoEConfig(num_experts=8, hidden_size=128,
                                   intermediate_size=256))
    bk = fm.get_bookkeeping()
    assert bk["mesh"]["ep"] == 8
    assert sorted(e for v in bk["local_experts"].values() for e in v) == \
        list(range(8))
    adj = ici_adjacency()
    p = tmp_path / "adj.txt"
    adj.export(str(p))
    text = p.read_text()
    assert "alpha" in text and "beta" in text
    bootstrap.finalize()


def test_multiprocess_launcher(devices, tmp_path):
    """Two real processes form a jax.distributed cluster through the
    launcher + bootstrap env protocol (the nvshmrun-equivalent path) and
    run the MoE worker end-to-end."""
    import os
    from flashmoe_tpu.runtime.launcher import run_workers

    cfg = tmp_path / "cfg.json"
    cfg.write_text(json.dumps({
        "num_experts": 2, "expert_top_k": 1, "hidden_size": 128,
        "intermediate_size": 256, "sequence_len": 128,
    }))
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",  # 1 CPU device per process -> 2 global
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = run_workers(2, config_path=str(cfg),
                         coordinator="127.0.0.1:9917")
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0


def test_slow_worker_shifts_placement(devices, tmp_path):
    """Measured placement end to end: two real processes run the full
    bootstrap (throughput probe + pairwise DCN probe + Decider); rank 1's
    measured rate is scaled down 8x and per-device memory is capped so the
    two workers must form one EP group — the Decider's rate-proportional
    assignment must then give the slow worker visibly fewer experts
    (reference: ``mT`` -> ``WorkerAttribute`` -> ``assign``,
    ``throughput.cuh:99-170``, ``decider.cuh:273-329``)."""
    import os
    from flashmoe_tpu.runtime.launcher import run_workers

    out = tmp_path / "placement"
    env = {
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "XLA_FLAGS": "",  # 1 CPU device per process -> 2 global
        # each worker holds 3MB; 8 experts x 0.52MB need ~4.2MB -> a single
        # worker is infeasible, the pair must merge into one EP group
        "FLASHMOE_MEMORY_GB": "0.003",
        "FLASHMOE_PLACEMENT_OUT": str(out),
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rc = run_workers(
            2, coordinator="127.0.0.1:9919",
            per_rank_env={1: {"FLASHMOE_THROUGHPUT_SCALE": "0.125"}},
            worker_module="tests._placement_worker",
        )
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rc == 0
    rec = json.loads((tmp_path / "placement.rank0.json").read_text())
    counts = {int(k): v for k, v in rec["counts"].items()}
    assert rec["groups"] == [[0, 1]], rec  # memory forced one EP group
    assert counts[0] + counts[1] == 8
    assert counts[0] > counts[1], (
        f"slow worker should hold fewer experts: {counts}"
    )


def test_worker_cli(devices):
    """The worker runs end-to-end as a subprocess (reference worker.py)."""
    import os
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = subprocess.run(
        [sys.executable, "-m", "flashmoe_tpu.runtime.worker"],
        capture_output=True, text=True, env=env, timeout=300,
        cwd=__import__("pathlib").Path(__file__).parent.parent,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["finite"] is True
    assert rec["rank"] == 0
