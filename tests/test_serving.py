"""Serving subsystem: paged KV cache, continuous-batching engine,
decode-shaped planner split, serving observability.

The headline drill is the ISSUE acceptance: a seeded multi-request CPU
run sustaining 8 concurrent requests with joins and retirements
mid-flight whose outputs are token-bit-equal to the same prompts
decoded one at a time through ``generate()``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig
from flashmoe_tpu.models.generate import generate
from flashmoe_tpu.models.transformer import init_params
from flashmoe_tpu.serving.engine import (
    Request, ServeConfig, ServingEngine,
)
from flashmoe_tpu.serving.kvcache import (
    SCRATCH_PAGE, PagePool, ctx_pages_bucket, gather_ctx,
    init_paged_cache, prompt_pad, store_prefill, store_token,
)
from flashmoe_tpu.serving.loadgen import (
    build_requests, serve_load_sweep, tiny_config,
)
from flashmoe_tpu.utils.telemetry import FlightRecorder, Metrics

CFG = tiny_config()


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def prompts():
    return jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0,
                              CFG.vocab_size)


def _requests(prompts, n, max_new=6, **kw):
    return [Request(rid=i, prompt=tuple(int(t) for t in prompts[i]),
                    max_new_tokens=max_new, **kw) for i in range(n)]


def _oracle(params, prompts, i, max_new=6):
    return np.asarray(generate(params, prompts[i:i + 1], CFG,
                               max_new_tokens=max_new))[0]


# ----------------------------------------------------------------------
# Paged KV cache
# ----------------------------------------------------------------------

def test_page_pool_lifo_reuse_and_errors():
    pool = PagePool(8)                      # pages 1..7 allocatable
    assert pool.free_pages == 7
    a = pool.alloc(3)
    b = pool.alloc(2)
    assert a == [1, 2, 3] and b == [4, 5]
    assert pool.used_pages == 5
    assert pool.alloc(3) is None            # no partial allocation
    pool.free(a)
    # LIFO: the freed pages come back in the SAME order — an evictee's
    # pages are exactly the next admission's pages
    assert pool.alloc(3) == [1, 2, 3]
    with pytest.raises(ValueError, match="double free"):
        pool.free(b + b)
    with pytest.raises(ValueError, match="out of range"):
        pool.free([SCRATCH_PAGE])


def test_ctx_bucketing():
    # 9 tokens at page 4, bucket 2 -> 3 pages rounds up to 4
    assert ctx_pages_bucket(9, 4, 2, 8) == 4
    assert ctx_pages_bucket(1, 4, 2, 8) == 2
    assert ctx_pages_bucket(10_000, 4, 2, 8) == 8   # clamped
    assert prompt_pad(5, 8) == 8
    assert prompt_pad(8, 8) == 8
    assert prompt_pad(9, 8) == 16


def test_paged_store_gather_roundtrip():
    """store_prefill + store_token + gather_ctx reproduce a dense K/V
    run exactly (the block-table indirection is pure reindexing)."""
    cache = init_paged_cache(CFG, num_pages=8, page_size=4)
    nkv, dh = CFG.resolved_num_kv_heads, CFG.resolved_head_dim
    l = CFG.num_layers
    seq = jax.random.normal(jax.random.PRNGKey(2), (l, nkv, 8, dh),
                            CFG.dtype)
    page_ids = jnp.asarray([3, 5], jnp.int32)       # non-contiguous
    kp = store_prefill(cache.k_pages, seq, page_ids)
    # one decode token at position 8 goes into a third page
    tok = jax.random.normal(jax.random.PRNGKey(3), (1, nkv, dh),
                            CFG.dtype)
    kp = kp.at[0].set(store_token(kp[0], tok, jnp.asarray([6]),
                                  jnp.asarray([0])))
    bt = jnp.asarray([[3, 5, 6]], jnp.int32)        # this slot's table
    got = gather_ctx(kp[0], bt)                     # [1, nkv, 12, dh]
    np.testing.assert_array_equal(np.asarray(got[0, :, :8]),
                                  np.asarray(seq[0]))
    np.testing.assert_array_equal(np.asarray(got[0, :, 8]),
                                  np.asarray(tok[0]))


def test_engine_rejects_capacity_configs(params):
    with pytest.raises(ValueError, match="dropless"):
        ServingEngine(params, CFG.replace(drop_tokens=True))


def test_serve_config_validation():
    with pytest.raises(ValueError, match="prompt_bucket"):
        ServeConfig(page_size=8, prompt_bucket=4)
    with pytest.raises(ValueError, match="ctx_bucket_pages"):
        ServeConfig(ctx_bucket_pages=99, max_pages_per_slot=4)
    with pytest.raises(ValueError, match="scratch"):
        ServeConfig(num_pages=1)
    with pytest.raises(ValueError, match="empty prompt"):
        Request(rid=0, prompt=())
    with pytest.raises(ValueError, match="top_p"):
        Request(rid=0, prompt=(1,), top_p=0.0)


def test_submit_rejects_requests_the_pool_can_never_serve(params):
    """A request whose lifetime exceeds the whole page pool must be
    rejected at submit — not spin the engine through max_steps with a
    permanently-starved queue head."""
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=2, page_size=8, num_pages=4,
                    max_pages_per_slot=8, ctx_bucket_pages=1,
                    prompt_bucket=8))
    # slot context (64) admits it, but the pool holds only 3 pages
    with pytest.raises(ValueError, match="pool"):
        engine.submit(Request(rid=0, prompt=tuple(range(1, 25)),
                              max_new_tokens=8))


# ----------------------------------------------------------------------
# The acceptance drill
# ----------------------------------------------------------------------

def test_drill_8_concurrent_bit_equal_vs_generate(params, prompts):
    """Seeded drill: 8 concurrent requests joining (staggered
    arrivals) and retiring mid-flight; engine outputs token-bit-equal
    to one-at-a-time ``generate()``; TTFT/TPOT/queue-depth/occupancy
    flow through the flight recorder."""
    mx = Metrics()
    recorder = FlightRecorder()
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=8, page_size=8, num_pages=32,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        recorder=recorder, metrics_obj=mx)
    reqs = _requests(prompts, 8)
    out = engine.run(reqs, arrivals=[0, 0, 0, 0, 1, 1, 2, 3])

    s = engine.summary()
    assert s["completed"] == 8
    assert s["max_active"] == 8                 # sustains 8 concurrent
    admits = [d for d in mx.decisions
              if d["decision"] == "serve.admit"]
    retires = [d for d in mx.decisions
               if d["decision"] == "serve.retire"]
    assert len(admits) == 8 and len(retires) == 8
    # joins happen mid-flight (after step 0) and before the first
    # retirement completes the run
    assert max(d["step"] for d in admits) > 0
    assert min(d["step"] for d in retires) \
        > min(d["step"] for d in admits)
    # bit-equal token streams vs the single-request decoder
    for i in range(8):
        np.testing.assert_array_equal(
            np.asarray(out[i]), _oracle(params, prompts, i))
    # observability: TTFT/TPOT on retires + step records carry queue
    # depth and cache occupancy
    assert all(d["ttft_ms"] is not None for d in retires)
    assert all(d["tpot_ms"] is not None for d in retires)
    steps = [r for r in recorder.records
             if r.get("kind") == "serve_step"]
    req_recs = [r for r in recorder.records
                if r.get("kind") == "serve_request"]
    assert steps and len(req_recs) == 8
    assert all("queue_depth" in r and "cache_occupancy" in r
               for r in steps)
    assert s["ttft_ms_mean"] is not None


def test_eviction_under_page_pressure_bit_equal(params, prompts):
    """A starved pool forces preemption: the youngest request is
    evicted (serve.evict), its pages are reused, it re-prefills and
    completes — outputs still bit-equal."""
    mx = Metrics()
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=4, page_size=8, num_pages=8,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        metrics_obj=mx)
    out = engine.run(_requests(prompts, 4, max_new=10))
    s = engine.summary()
    assert s["evictions"] > 0 and s["completed"] == 4
    evicts = [d for d in mx.decisions
              if d["decision"] == "serve.evict"]
    resumed = [d for d in mx.decisions
               if d["decision"] == "serve.admit" and d["resumed"]]
    assert evicts and len(resumed) == len(evicts)
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(out[i]), _oracle(params, prompts, i,
                                        max_new=10))


def test_bucketed_jit_policy(params, prompts):
    """Requests with different prompt lengths inside one bucket share
    one prefill compilation, and the decode gather length stays on
    bucket boundaries — the join-without-recompile policy."""
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=4, page_size=8, num_pages=32,
                    max_pages_per_slot=4, ctx_bucket_pages=2,
                    prompt_bucket=8))
    reqs = [Request(rid=i, prompt=tuple(int(t) for t in
                                        prompts[i][:4 + i]),
                    max_new_tokens=4) for i in range(3)]
    engine.run(reqs, arrivals=[0, 1, 2])
    s = engine.summary()
    assert s["prefill_buckets"] == [8]     # 3 lengths, one bucket
    assert s["decode_buckets"] == [2]      # one ctx bucket


def test_sampled_requests_deterministic(params, prompts):
    """Per-request seeded sampling: identical traces produce identical
    outputs, and sampling params ride per request."""
    def run():
        engine = ServingEngine(
            params, CFG,
            ServeConfig(max_batch=4, page_size=8, num_pages=32,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8))
        reqs = _requests(prompts, 3, max_new=5, temperature=0.8,
                         top_k=20, seed=11)
        return engine.run(reqs)

    a, b = run(), run()
    for i in range(3):
        np.testing.assert_array_equal(np.asarray(a[i]),
                                      np.asarray(b[i]))
        toks = a[i][8:]
        assert all(0 <= t < CFG.vocab_size for t in toks)


def test_stop_token_retires_early(params, prompts):
    """A request whose stop set contains its first greedy token
    retires after exactly one emission."""
    first = int(_oracle(params, prompts, 0, max_new=1)[-1])
    mx = Metrics()
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=4, page_size=8, num_pages=32,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        metrics_obj=mx)
    out = engine.run(_requests(prompts, 1, max_new=8,
                               stop_tokens=(first,)))
    assert list(out[0][8:]) == [first]
    retire = [d for d in mx.decisions
              if d["decision"] == "serve.retire"][0]
    assert retire["tokens"] == 1


# ----------------------------------------------------------------------
# Serving SLOs through the watchdog
# ----------------------------------------------------------------------

def test_ttft_slo_breach_through_watchdog(params, prompts):
    from flashmoe_tpu.profiler.slo import SLOConfig, SLOWatchdog

    mx = Metrics()
    dog = SLOWatchdog(SLOConfig(ttft_ms=1e-6, tpot_ms=1e9),
                      metrics=mx)
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=4, page_size=8, num_pages=32,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        slo=dog, metrics_obj=mx)
    engine.run(_requests(prompts, 2, max_new=3))
    breaches = [d for d in mx.decisions
                if d["decision"] == "slo.breach"]
    assert breaches and all(b["target"] == "ttft" for b in breaches)
    assert {b["request"] for b in breaches} == {0, 1}
    assert mx.counters["slo.breaches"] >= 2


def test_slo_config_serving_budgets():
    from flashmoe_tpu.profiler.slo import SLOConfig, SLOWatchdog

    with pytest.raises(ValueError, match="ttft_ms"):
        SLOConfig(ttft_ms=-1)
    slo = SLOConfig.from_dict({"ttft_ms": 50, "tpot_ms": 5})
    assert slo.ttft_ms == 50 and slo.tpot_ms == 5
    mx = Metrics()
    dog = SLOWatchdog(slo, metrics=mx)
    assert dog.observe_request(3, 7, ttft_ms=10, tpot_ms=1) == []
    ev = dog.observe_request(4, 8, ttft_ms=80, tpot_ms=9)
    assert [e["target"] for e in ev] == ["ttft", "tpot"]
    assert all(e["request"] == 8 for e in ev)


# ----------------------------------------------------------------------
# Decode-shaped planner split
# ----------------------------------------------------------------------

def test_decode_mode_golden_gated():
    """The decode-vs-training plan split is CI-gated: recompute the
    golden decode section and require at least one config where decode
    resolves a DIFFERENT plan than training."""
    from flashmoe_tpu.planner.golden import (
        GOLDEN_PATH, golden_snapshot,
    )

    with open(GOLDEN_PATH) as f:
        frozen = json.load(f)
    live = golden_snapshot()
    assert live["decode"] == frozen["decode"], (
        "decode-mode golden plans moved; if intentional regenerate "
        "with python -m flashmoe_tpu.planner --regen-golden")
    assert any(g["differs"] for gens in frozen["decode"].values()
               for g in gens.values()), (
        "no golden config resolves a different decode-priced plan — "
        "the serving planner split lost its teeth")
    # the reference config flips PATH (not just chunks): collective in
    # training, ragged at decode token counts
    ref = frozen["decode"]["reference"]["v5e"]
    assert ref["training"]["winner"] != ref["decode"]["winner"]


def test_resolve_moe_plan_decode_mode(monkeypatch):
    from flashmoe_tpu.planner.select import (
        _cached_backend, resolve_moe_plan,
    )

    monkeypatch.setenv("FLASHMOE_TPU_GEN", "v5e")
    for var in ("FLASHMOE_TUNING_FILE", "FLASHMOE_BENCH_RECORDS",
                "FLASHMOE_MOCK_SLICES"):
        monkeypatch.delenv(var, raising=False)
    _cached_backend.cache_clear()
    cfg = BENCH_CONFIGS["reference"].replace(moe_backend="auto", ep=8)
    train = resolve_moe_plan(cfg)
    decode = resolve_moe_plan(cfg, mode="decode", decode_tokens=64)
    assert decode != train
    assert decode[0] == "ragged"
    # the serving_mode selector field routes the same regime without
    # the call-site axis (the transformer hook's path)
    via_field = resolve_moe_plan(cfg.replace(serving_mode="decode"))
    assert via_field[0] == decode[0]
    _cached_backend.cache_clear()


def test_decode_shape_and_mode_validation():
    from flashmoe_tpu.planner.model import (
        decode_shape, predict_paths,
    )

    cfg = BENCH_CONFIGS["reference"]
    d = decode_shape(cfg, 8, 100)
    assert d.tokens == 104 and not d.is_training  # rounded up to d
    assert decode_shape(cfg, 8, 0).tokens == 64    # 0 = default batch
    with pytest.raises(ValueError, match="decode_tokens"):
        decode_shape(cfg, 8, -4)
    with pytest.raises(ValueError, match="mode"):
        predict_paths(cfg, 8, "v5e", mode="inference")
    with pytest.raises(ValueError, match="serving_mode"):
        cfg.replace(serving_mode="train")


def test_serve_plan_decision_recorded(params):
    mx = Metrics()
    engine = ServingEngine(
        params, CFG.replace(serving_mode="decode"),
        ServeConfig(max_batch=4, page_size=8, num_pages=16,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        metrics_obj=mx)
    plan = [d for d in mx.decisions if d["decision"] == "serve.plan"]
    assert len(plan) == 1
    assert plan[0]["decode_tokens"] == 4
    assert engine.decode_plan and engine.prefill_plan


# ----------------------------------------------------------------------
# Prefill/decode pools (inference-mode Decider)
# ----------------------------------------------------------------------

def test_serving_pools_split():
    from flashmoe_tpu.parallel.topology import Adjacency, WorkerAttr
    from flashmoe_tpu.serving.pools import plan_serving_pools

    n = 4
    alpha = np.full((n, n), 1e-3)
    beta = np.full((n, n), 1e-5)
    np.fill_diagonal(alpha, 0.0)
    np.fill_diagonal(beta, 0.0)
    adj = Adjacency(alpha=alpha, beta=beta)
    # device 2 is the fastest: decode (latency-critical) must take it
    rates = [1.0, 1.0, 4.0, 1.0]
    workers = [WorkerAttr(throughput=r, memory_gb=16.0) for r in rates]
    cfg = BENCH_CONFIGS["reference"]
    plan = plan_serving_pools(adj, workers, cfg, decode_share=0.5,
                              record=False)
    assert 2 in plan.decode_devices
    assert plan.prefill_devices and plan.decode_devices
    assert set(plan.prefill_devices) | set(plan.decode_devices) \
        == set(range(n))
    assert not set(plan.prefill_devices) & set(plan.decode_devices)
    assert plan.prefill_ms > 0 and plan.decode_ms > 0
    with pytest.raises(ValueError, match="decode_share"):
        plan_serving_pools(adj, workers, cfg, decode_share=1.5)


# ----------------------------------------------------------------------
# CLI + load sweep
# ----------------------------------------------------------------------

def test_serving_cli_summary_and_artifacts(tmp_path, capsys):
    from flashmoe_tpu.serving.__main__ import main

    obs = tmp_path / "obs"
    rc = main(["--requests", "2", "--max-batch", "2", "--max-new", "3",
               "--prompt-len", "8", "--obs-dir", str(obs),
               "--ttft-slo-ms", "0.000001"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["completed"] == 2
    assert rec["slo_breaches"] >= 2
    assert rec["tokens_per_sec"] is not None
    flight = (obs / "flight.jsonl").read_text().splitlines()
    assert any(json.loads(l).get("kind") == "serve_step"
               for l in flight)
    decisions = (obs / "decisions.jsonl").read_text()
    assert "serve.retire" in decisions and "slo.breach" in decisions


def test_serve_load_sweep_records():
    recs = serve_load_sweep([2, 1], n_requests=2, max_batch=2,
                            max_new=3, prompt_len=8)
    assert len(recs) == 2
    for rec in recs:
        assert rec["metric"].startswith("serve_load[")
        assert rec["unit"] == "tokens_per_sec" and rec["value"] > 0
        assert "ttft_ms_p50" in rec and "tpot_ms_p50" in rec
        assert rec["completed"] == 2
    assert recs[0]["vs_baseline"] == 1.0


def test_build_requests_deterministic():
    a, ar = build_requests(4, vocab=256, prompt_len=8, max_new=4,
                           seed=3, arrival_every=2)
    b, br = build_requests(4, vocab=256, prompt_len=8, max_new=4,
                           seed=3, arrival_every=2)
    assert [r.prompt for r in a] == [r.prompt for r in b]
    assert ar == br == [0, 0, 2, 2]


# ----------------------------------------------------------------------
# observe --serving
# ----------------------------------------------------------------------

def test_observe_serving_report(params, prompts, tmp_path, capsys):
    from flashmoe_tpu import observe

    mx = Metrics()
    recorder = FlightRecorder()
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=4, page_size=8, num_pages=32,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        recorder=recorder, metrics_obj=mx)
    engine.run(_requests(prompts, 3, max_new=3))
    flight = tmp_path / "flight.jsonl"
    dec = tmp_path / "decisions.jsonl"
    recorder.export_jsonl(str(flight))
    mx.dump_decisions_jsonl(str(dec))

    rc = observe.main(["--serving", "--json", str(flight), str(dec)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip())
    assert rep["requests_completed"] == 3
    assert rep["ttft_ms"]["p50"] is not None
    assert rep["tpot_ms"] is not None
    assert rep["queue_depth"]["max"] >= 0
    assert rep["cache_occupancy"]["peak"] > 0
    assert rep["plan"] is not None
    assert rep["admissions"] == 3

    # text rendering + the no-data exit code
    rc = observe.main(["--serving", str(flight), str(dec)])
    assert rc == 0
    assert "TTFT" in capsys.readouterr().out
    empty = tmp_path / "empty.jsonl"
    empty.write_text('{"step": 1}\n')
    assert observe.main(["--serving", str(empty)]) == 2


# ----------------------------------------------------------------------
# Live telemetry plane on the real engine (PR 13 acceptance)
# ----------------------------------------------------------------------

def test_live_plane_drill_8_concurrent_traced_bit_identical(params,
                                                            prompts):
    """The acceptance drill: 8 concurrent requests under page pressure
    (at least one evicted/re-prefilled), tracing + scrape server ON —
    outputs token-bit-equal to the plane-off engine, every request
    reconstructs to a contiguous per-request Perfetto track (no orphan
    spans, eviction gap visible) passing validate_trace, and a LIVE
    /metrics scrape mid-drill returns parseable exposition text with
    the TTFT/TPOT summary quantiles."""
    import urllib.request

    from flashmoe_tpu.profiler.export import (
        request_trace_document, validate_trace,
    )

    # pool sized so all 8 requests are concurrently resident (2 pages
    # each) and the THIRD page (length 16, ~8 decode steps in) starves
    # the pool: 8-concurrent first, eviction/re-prefill after
    serve = ServeConfig(max_batch=8, page_size=8, num_pages=20,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8)
    reqs = _requests(prompts, 8, max_new=10)
    arrivals = [0, 0, 0, 0, 1, 1, 2, 3]

    m_on = Metrics()
    on = ServingEngine(params, CFG, serve, metrics_obj=m_on,
                       tracer=True, telemetry_port=0)
    try:
        for req, arr in zip(reqs, arrivals):
            on.submit(req, arr)
        # drive until the first retirement seeds the sketches, then
        # scrape while work is still in flight
        while on.pending() and "serve.ttft_ms" not in m_on.sketches:
            on.step()
        assert on.pending()
        url = f"http://127.0.0.1:{on.telemetry.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as r:
            body = r.read().decode()
            assert r.headers.get("Content-Type") == \
                "text/plain; version=0.0.4"
        assert 'flashmoe_serve_ttft_ms{quantile="' in body
        assert 'flashmoe_serve_tpot_ms{quantile="' in body
        assert "flashmoe_serve_queue_depth" in body
        while on.pending():
            on.step()
        out_on = dict(on.outputs)
        s_on = on.summary()
    finally:
        on.close()

    assert s_on["completed"] == 8 and s_on["max_active"] == 8
    assert s_on["evictions"] > 0            # re-prefill cycle exercised

    # plane off: bit-identical token streams
    off = ServingEngine(params, CFG, serve, metrics_obj=Metrics())
    out_off = off.run(_requests(prompts, 8, max_new=10), arrivals)
    for i in range(8):
        np.testing.assert_array_equal(np.asarray(out_on[i]),
                                      np.asarray(out_off[i]))

    # every request: contiguous track, eviction gaps visible
    tr = on.tracer
    assert tr.validate() == []
    assert len(tr.requests) == 8
    evicted = [rid for rid, st in tr.requests.items() if st.evictions]
    assert evicted
    for rid in evicted:
        gaps = [s for s in tr.request_track(rid)
                if s["name"] == "serve.queued" and s.get("resumed")]
        assert len(gaps) == tr.requests[rid].evictions
    doc = request_trace_document(tr)
    assert validate_trace(doc) == []
    assert len({e["pid"] for e in doc["traceEvents"]}) == 8
    # every retirement closed its trace
    traces = [d for d in m_on.decisions
              if d["decision"] == "serve.trace"]
    assert len(traces) == 8
    assert {d["rid"] for d in traces} == set(range(8))


def test_engine_summary_uses_sketches_not_decision_scan(params,
                                                        prompts):
    """summary() reads the O(1)-memory retire sketches — a foreign
    decision stream (e.g. another engine on the same Metrics) cannot
    change this engine's numbers, and p99 is reported."""
    mx = Metrics()
    engine = ServingEngine(
        params, CFG,
        ServeConfig(max_batch=4, page_size=8, num_pages=32,
                    max_pages_per_slot=4, ctx_bucket_pages=1,
                    prompt_bucket=8),
        metrics_obj=mx)
    engine.run(_requests(prompts, 3, max_new=3))
    s = engine.summary()
    assert s["ttft_ms_mean"] is not None
    assert s["ttft_ms_p99"] >= s["ttft_ms_mean"] * 0.5
    assert mx.sketches["serve.ttft_ms"].n == 3
    assert mx.sketches["serve.step_ms"].n == s["steps"]
    # windowed rates ride the gauges
    assert "serve.tokens_per_s" in mx.gauges


# ----------------------------------------------------------------------
# Speculative multi-token decoding (ISSUE 20)
# ----------------------------------------------------------------------

def _spec_serve(speculate=None, **kw):
    from flashmoe_tpu.serving.speculate import SpecConfig

    base = dict(max_batch=4, page_size=8, num_pages=32,
                max_pages_per_slot=4, ctx_bucket_pages=1,
                prompt_bucket=8)
    base.update(kw)
    if speculate is not None:
        base["speculate"] = SpecConfig(draft_tokens=speculate)
    return ServeConfig(**base)


@pytest.fixture(scope="module")
def spec_prompts():
    """Repetitive prompts (tiled bigram motifs): the n-gram drafter
    has suffix matches to propose from, so the verify path actually
    exercises acceptance instead of the empty-draft fallthrough."""
    motifs = jax.random.randint(jax.random.PRNGKey(7), (8, 2), 0,
                                CFG.vocab_size)
    return jnp.asarray([[int(motifs[i][j % 2]) for j in range(8)]
                        for i in range(8)])


def test_speculative_decode_bit_equal_greedy(params, spec_prompts):
    """The exactness acceptance: speculation on emits token-bit-equal
    streams to the non-speculative oracle, while actually accepting
    drafts (not vacuously passing through the no-draft path)."""
    engine = ServingEngine(params, CFG, _spec_serve(speculate=3))
    out = engine.run(_requests(spec_prompts, 4, max_new=8),
                     arrivals=[0, 0, 1, 2])
    snap = engine.spec_snapshot()
    assert snap["spec_drafted"] > 0, "drill never drafted — vacuous"
    assert snap["spec_accepted"] > 0
    assert snap["spec_tokens_per_step"] > 1.0
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(out[i]),
            _oracle(params, spec_prompts, i, max_new=8))


def test_speculative_decode_bit_equal_sampled(params, spec_prompts):
    """Exact rejection sampling: the per-request fold_in key stream
    makes speculative output bit-equal at every sampling arm, and
    bit-equal across batch-composition changes (staggered arrivals vs
    all-at-once)."""
    def run(spec, arrivals=None):
        engine = ServingEngine(params, CFG, _spec_serve(
            speculate=3 if spec else None))
        reqs = _requests(spec_prompts, 4, max_new=6, temperature=0.8,
                         top_k=20, top_p=0.9, seed=21)
        return engine.run(reqs, arrivals=arrivals)

    base = run(False)
    spec = run(True)
    stagger = run(True, arrivals=[0, 1, 2, 3])
    for i in range(4):
        np.testing.assert_array_equal(np.asarray(base[i]),
                                      np.asarray(spec[i]))
        np.testing.assert_array_equal(np.asarray(base[i]),
                                      np.asarray(stagger[i]))


def test_speculative_eviction_bit_equal(params, spec_prompts):
    """A starved pool evicts mid-speculation; the DraftState rebuilds
    from the resumed prompt (prompt + delivered tokens), and the
    re-prefilled request completes bit-equal."""
    mx = Metrics()
    engine = ServingEngine(params, CFG,
                           _spec_serve(speculate=3, num_pages=8),
                           metrics_obj=mx)
    out = engine.run(_requests(spec_prompts, 4, max_new=10))
    s = engine.summary()
    assert s["evictions"] > 0 and s["completed"] == 4
    assert s["spec_drafted"] > 0
    for i in range(4):
        np.testing.assert_array_equal(
            np.asarray(out[i]),
            _oracle(params, spec_prompts, i, max_new=10))


def test_spec_stats_ride_retire_and_flight_records(params,
                                                   spec_prompts):
    """Per-request acceptance stats land on serve.retire decisions and
    serve_request flight records; per-step spec_tokens/spec_on ride
    serve_step records; summary() and the health snapshot carry the
    fleet numbers."""
    mx = Metrics()
    recorder = FlightRecorder()
    engine = ServingEngine(params, CFG, _spec_serve(speculate=3),
                           metrics_obj=mx, recorder=recorder)
    engine.run(_requests(spec_prompts, 4, max_new=8))
    retires = [d for d in mx.decisions
               if d["decision"] == "serve.retire"]
    assert retires and all("spec_drafted" in d and "accept_rate" in d
                           for d in retires)
    req_recs = [r for r in recorder.records
                if r.get("kind") == "serve_request"]
    assert req_recs and all("spec_accepted" in r for r in req_recs)
    steps = [r for r in recorder.records
             if r.get("kind") == "serve_step"]
    assert steps and all("spec_tokens" in r and "spec_on" in r
                         for r in steps)
    assert sum(r["spec_tokens"] for r in steps) \
        == engine.spec_snapshot()["spec_accepted"]
    s = engine.summary()
    assert s["spec_drafted"] == engine.spec_snapshot()["spec_drafted"]
    assert engine._health_snapshot()["spec"]["spec_on"] is True
    # the recorder dump reduces to the same numbers through the
    # host-side consumer twin
    from flashmoe_tpu.ops.stats import speculation_summary

    agg = speculation_summary(recorder.records)
    assert agg["spec_drafted"] == s["spec_drafted"]
    assert agg["spec_accepted"] == s["spec_accepted"]
    assert agg["spec_steps"] > 0


def test_spec_off_graph_and_config_identity(params, prompts):
    """speculate=None is the off value: the ServeConfig is EQUAL to
    one that never named the field (one jit cache entry), the engine
    builds no verify function, and the decode step's traced graph is
    byte-identical before vs after a speculative engine ran."""
    from flashmoe_tpu.serving.engine import _paged_decode_step
    from flashmoe_tpu.staticcheck.graph import jaxpr_text

    assert _spec_serve() == _spec_serve(speculate=None)

    def decode_jaxpr():
        sv = _spec_serve()
        k, v = init_paged_cache(CFG, sv.num_pages, sv.page_size)
        toks = jnp.zeros((sv.max_batch,), jnp.int32)
        pos = jnp.zeros((sv.max_batch,), jnp.int32)
        tables = jnp.zeros((sv.max_batch, sv.ctx_bucket_pages),
                           jnp.int32)
        closed = jax.make_jaxpr(
            lambda *a: _paged_decode_step.__wrapped__(params, CFG, *a))
        return jaxpr_text(closed(k, v, toks, tables, pos).jaxpr)

    before = decode_jaxpr()
    engine = ServingEngine(params, CFG, _spec_serve(speculate=2))
    engine.run(_requests(prompts, 1, max_new=3))
    assert decode_jaxpr() == before
    plain = ServingEngine(params, CFG, _spec_serve())
    assert plain._spec is None
    assert "spec_drafted" not in plain.summary()


def test_set_speculate_morphs_and_validates(params, spec_prompts):
    """set_speculate flips the live engine off/on with serve.spec
    decisions; enabling on an engine that never armed a SpecConfig is
    a config error."""
    mx = Metrics()
    engine = ServingEngine(params, CFG, _spec_serve(speculate=3),
                           metrics_obj=mx)
    engine.set_speculate(False, reason="drill")
    assert engine._spec is None
    out = engine.run(_requests(spec_prompts, 2, max_new=6))
    assert engine.spec_snapshot()["spec_drafted"] == 0
    for i in range(2):
        np.testing.assert_array_equal(
            np.asarray(out[i]),
            _oracle(params, spec_prompts, i, max_new=6))
    morphs = [d for d in mx.decisions
              if d["decision"] == "serve.spec"
              and d.get("event") == "morph_off"]
    assert len(morphs) == 1
    plain = ServingEngine(params, CFG, _spec_serve())
    with pytest.raises(ValueError, match="speculate"):
        plain.set_speculate(True)


def test_serve_load_sweep_speculate_arm():
    """bench --serve --speculate contract: spec=kN metric identity,
    per-record acceptance stats, the equal-SLO baseline TPOT
    comparison, and the asserted exactness bit."""
    recs = serve_load_sweep([3], n_requests=4, max_batch=2, max_new=5,
                            speculate=2)
    assert len(recs) == 1
    r = recs[0]
    assert ",spec=k2]" in r["metric"]
    assert r["bit_equal_to_baseline"] is True
    assert r["spec_drafted"] >= r["spec_accepted"] >= 0
    assert 0.0 <= r["accept_rate"] <= 1.0
    assert r["spec_tokens_per_step"] >= 1.0
    assert r["baseline_tpot_ms_p50"] is not None
    assert "baseline_outputs" not in r   # payload stays JSON-sized


def test_draft_state_ngram_index():
    """DraftState unit: suffix-match drafting, continuation fallback
    to the previous occurrence, sync after external token appends."""
    from flashmoe_tpu.serving.speculate import (
        DraftState, SpecConfig, spec_stats_fields,
    )

    spec = SpecConfig(draft_tokens=3, ngram=2)
    ds = DraftState(spec, [1, 2, 3, 1, 2])
    assert ds.draft(3) == [3, 1, 2]        # continue the seen bigram
    ds.extend([3])                         # now ...1 2 3; suffix [2,3]
    assert ds.draft(3) == [1, 2, 3]
    ds.sync([1, 2, 3, 1, 2, 3, 9, 9])      # external append resyncs
    assert ds.draft(2) == []               # suffix [9,9] never seen
    with pytest.raises(ValueError):
        SpecConfig(draft_tokens=0)
    with pytest.raises(ValueError):
        SpecConfig(ngram=0)
    with pytest.raises(ValueError):
        SpecConfig(source="magic")
    f = spec_stats_fields(4, 3, 2)
    assert f["accept_rate"] == 0.75
    assert f["spec_tokens_per_step"] == 2.5
