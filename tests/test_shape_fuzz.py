"""Deterministic shape-space fuzz: pseudo-random configurations swept
through the Pallas layer stack vs the dense oracle.

The tile/schedule resolution logic (`_resolve_tiles`, `_fused_schedule`,
capacity padding, gate kernel selection) branches on divisibility and
budget boundaries; targeted tests pin the known corners, this sweep
walks a seeded sample of the space so a future chooser change that
breaks an odd shape fails CI instead of a hardware window."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops.moe import moe_layer

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _fuzz_cfg(seed: int) -> MoEConfig:
    """One pseudo-random (but fully deterministic) configuration."""
    r = np.random.RandomState(seed)
    e = int(r.choice([2, 4, 8, 16]))
    return MoEConfig(
        num_experts=e,
        expert_top_k=int(r.randint(1, min(4, e) + 1)),
        hidden_size=int(r.choice([64, 128, 192, 256])),
        intermediate_size=int(r.choice([64, 128, 320, 512])),
        sequence_len=int(r.choice([64, 128, 264, 512])),
        capacity_factor=float(r.choice([0.5, 1.0, 1.25, 2.0])),
        drop_tokens=bool(r.choice([True, False])),
        gated_ffn=bool(r.choice([True, False])),
        hidden_act=str(r.choice(["relu", "gelu", "silu"])),
        **F32,
    )


# seeds chosen once; the point is a fixed, diverse sample — several land
# on non-128-multiple capacities, tiny row tiles, k=1, and CF<1 drops
_SEEDS = list(range(10))


@pytest.mark.parametrize("seed", _SEEDS[:2])
def test_fuzz_single_device_fast(seed):
    _run_one(_fuzz_cfg(seed))


@pytest.mark.slow
@pytest.mark.parametrize("seed", _SEEDS[2:])
def test_fuzz_single_device(seed):
    _run_one(_fuzz_cfg(seed))


def _run_one(cfg: MoEConfig):
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    got = moe_layer(params, x, cfg, use_pallas=True, interpret=True)
    assert np.isfinite(np.asarray(got.out)).all(), cfg
    want_out = moe_layer(params, x, cfg, use_pallas=False).out
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want_out), rtol=3e-4, atol=3e-4,
        err_msg=repr(cfg),
    )
    if not cfg.drop_tokens:
        want, _ = reference_moe(params, x, cfg)
        np.testing.assert_allclose(
            np.asarray(got.out), np.asarray(want), rtol=3e-4, atol=3e-4,
            err_msg=repr(cfg),
        )


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 4, 7])
def test_fuzz_fused_ep(seed, monkeypatch, devices):
    """The same sweep through the fused RDMA layer on an ep mesh whose
    width the seed picks (2 = per-source schedule, 4 = arrival-batched
    default) — the full chooser matrix under fuzzed shapes.  Ambient
    schedule knobs cleared so the matrix actually varies by ep."""
    from flashmoe_tpu.parallel.ep import ep_moe_layer
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh

    monkeypatch.delenv("FLASHMOE_FUSED_BATCHED", raising=False)
    monkeypatch.delenv("FLASHMOE_FUSED_COMBINE", raising=False)
    cfg = _fuzz_cfg(seed)
    ep = 4 if cfg.num_experts % 4 == 0 else 2
    if cfg.num_experts % ep:
        pytest.skip("experts not divisible")
    cfg = cfg.replace(ep=ep, sequence_len=max(cfg.sequence_len, 64 * ep))
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    mesh = make_mesh(cfg, dp=1, devices=devices[:ep])
    got = fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)
    want = ep_moe_layer(params, x, cfg, mesh, use_pallas=False)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(want.out), rtol=3e-4, atol=3e-4,
        err_msg=repr(cfg),
    )
