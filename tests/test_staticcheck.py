"""Static-analysis subsystem (flashmoe_tpu/staticcheck/): the jaxpr
invariant engine, the collective census cross-check, the AST lint, and
the CLI — including planted violations proving each gate has teeth
(an unpriced collective, a leaked fp8 cast with the wire off, an
unregistered decision name, an unclassified MoEConfig knob).

Everything here is trace-only (abstract meshes, eval_shape parameter
shapes) — fast-lane material; this file IS the tier-1 wiring of
``python -m flashmoe_tpu.staticcheck --all`` (runtime budget documented
in docs/STATIC_ANALYSIS.md: ~20 s for the full matrix on CPU).
"""

import dataclasses
import subprocess
import sys
import warnings

import jax
import jax.numpy as jnp
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.staticcheck import registry as reg
from flashmoe_tpu.staticcheck.census import run_census
from flashmoe_tpu.staticcheck.invariants import run_invariants
from flashmoe_tpu.staticcheck.lint import (
    check_in_graph, run_lint,
)


# ----------------------------------------------------------------------
# The three engines, clean on the repo (module-scoped: one run each)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def invariant_result(devices):
    return run_invariants(devices=devices)


@pytest.fixture(scope="module")
def census_result(devices):
    return run_census(devices=devices)


def test_invariant_matrix_clean(invariant_result):
    """Every registered (backend, knob) combination holds its declared
    invariants — the generic engine that replaced the per-PR one-off
    jaxpr assertions."""
    assert invariant_result == []


def test_census_reconciles_every_golden_variant(census_result):
    """Acceptance bar: jaxpr-counted collective bytes reconcile against
    the analysis/planner models for every golden.json config x wire x
    chunks x path, with skips explicit and reasoned, never silent."""
    violations, rows = census_result
    assert violations == []
    keys = {(r.config, r.path, r.wire, r.chunks) for r in rows}
    # the full declared matrix ran: 3 configs x {off, e4m3} x chunk
    # variants x {flat, hierarchical, ragged}
    assert ("reference", "collective", "off", "serial") in keys
    assert ("reference", "hierarchical", "e4m3", "c4") in keys
    assert ("reference", "ragged", "e4m3", "c4") in keys
    assert ("deepseek", "hierarchical", "e4m3", "c4") in keys
    # mixtral has no chunk axis at d=8 (nLx=1): only serial variants
    assert not any(r.config == "mixtral" and r.chunks == "c4"
                   for r in rows)
    # deepseek's ragged rows are declared skips (shared experts), and
    # nothing else is skipped
    skips = [r for r in rows if r.note.startswith("skipped")]
    assert skips and all(r.config == "deepseek" and r.path == "ragged"
                         for r in skips)
    # the documented slack factors: capacity paths exact, ragged dense
    # fallback pads by d x chunks
    for r in rows:
        if r.note:
            continue
        want = {"serial": 1.0, "c4": 4.0}[r.chunks] * 8 \
            if r.path == "ragged" else 1.0
        assert r.bound_factor == want, (r.config, r.path, r.chunks)


def test_lint_clean_on_repo():
    assert run_lint() == []


@pytest.mark.slow
def test_cli_all_json(capsys, devices):
    """The CI entry point: ``--all`` runs every engine and exits 0 on
    the repo (nonzero path proven by the planted tests below)."""
    import json

    from flashmoe_tpu.staticcheck.__main__ import main

    assert main(["--all", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] and doc["violations"] == []
    assert set(doc["engines"]) == {"lint", "invariants", "census"}
    # 3 configs x (2 golden + 1 census-only dcn) wires x chunk variants
    # x 3 paths (declared skips included) = 45, plus the
    # quantized-store rows (ISSUE 15: 3 configs x 3 paths at
    # wire-off/serial — expert weights are rank-local, so int8 storage
    # must leave every collective untouched) = 54, plus the
    # kv-handoff-wire rows (ISSUE 16: 3 configs x 3 paths — the page
    # codec is a host boundary, so kv_wire_dtype must move NO
    # collective) = 63
    assert len(doc["engines"]["census"]["rows"]) == 63


def test_cli_exits_nonzero_on_violation(tmp_path):
    """Module entry point + exit-code contract, via a real subprocess
    on a planted lint violation (lint-only: no tracing, stays fast)."""
    bad = tmp_path / "bad.py"
    bad.write_text("from flashmoe_tpu.utils.telemetry import metrics\n"
                   'metrics.decision("planner.typo_name", x=1)\n')
    proc = subprocess.run(
        [sys.executable, "-m", "flashmoe_tpu.staticcheck", "--lint",
         "--paths", str(bad)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "planner.typo_name" in proc.stdout


# ----------------------------------------------------------------------
# Planted violations: each gate demonstrably fails when it should
# ----------------------------------------------------------------------

def test_planted_unpriced_collective_flagged(monkeypatch, devices):
    """(a) A collective the models do not price: an all_gather smuggled
    into the EP exchange trips the census."""
    import flashmoe_tpu.parallel.ep as ep_mod

    orig = ep_mod._exchange

    def leaky(t, axis, d, dcn_inner, *, reverse):
        extra = jax.lax.all_gather(t[:1], axis, tiled=True)
        t = t + 0 * extra[:1].astype(t.dtype)
        return orig(t, axis, d, dcn_inner, reverse=reverse)

    monkeypatch.setattr(ep_mod, "_exchange", leaky)
    violations, _rows = run_census(
        configs=["reference"], wires=["off"], chunks=["serial"],
        paths=["collective"], devices=devices)
    assert any(v.rule == "gather-count" for v in violations), violations


def test_planted_fp8_with_wire_off_flagged(monkeypatch, devices):
    """(b) An fp8 cast leaking into the wire-off graph trips the
    invariant engine's fp8-free rule."""
    import flashmoe_tpu.parallel.ep as ep_mod

    orig = ep_mod._exchange

    def sneaky(t, axis, d, dcn_inner, *, reverse):
        t = t.astype(jnp.float8_e4m3fn).astype(t.dtype)
        return orig(t, axis, d, dcn_inner, reverse=reverse)

    monkeypatch.setattr(ep_mod, "_exchange", sneaky)
    violations = run_invariants(knobs=["wire_dtype"],
                                backends=["collective"],
                                devices=devices,
                                include_coverage=False)
    assert any(v.rule == "fp8_free" for v in violations), violations


def test_planted_unregistered_decision_name(tmp_path):
    """(c) A typo'd decision-name literal trips the lint (the runtime
    warning alone would only fire if the line executed)."""
    bad = tmp_path / "typo.py"
    bad.write_text("from flashmoe_tpu.utils.telemetry import metrics\n"
                   'metrics.decision("planner.typo_name", x=1)\n'
                   'metrics.last_decision("planner.drift")\n')
    violations = run_lint(paths=[str(bad)])
    assert len(violations) == 1
    assert violations[0].rule == "decision-name"
    assert "planner.typo_name" in violations[0].detail


def test_planted_mispriced_model_flagged(monkeypatch, devices):
    """A deliberately mispriced comm model (both model sources shifted
    consistently, so only the graph can catch it) trips the census
    byte reconciliation."""
    import flashmoe_tpu.analysis as an

    orig = an.wire_row_bytes
    monkeypatch.setattr(
        an, "wire_row_bytes",
        lambda cfg, leg="dispatch", hop="ici": orig(cfg, leg, hop) / 2)
    violations, _rows = run_census(
        configs=["reference"], wires=["off"], chunks=["serial"],
        paths=["collective"], devices=devices)
    assert any(v.rule == "a2a-bytes" for v in violations), violations


def test_planted_in_graph_host_patterns(tmp_path):
    """time.time and a Python if on a jnp expression inside a jitted
    body are both flagged; a waived line is not."""
    f = tmp_path / "traced.py"
    f.write_text(
        "import time\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "\n"
        "def body(x):\n"
        "    t = time.time()\n"
        "    ok = time.time()  # staticcheck: ok test waiver\n"
        "    if jnp.any(x > 0):\n"
        "        return x * t * ok\n"
        "    return x\n"
        "\n"
        "f = jax.jit(body)\n")
    violations = check_in_graph([str(f)])
    rules = sorted(v.rule for v in violations)
    assert rules == ["in-graph-host-call", "tracer-branch"], violations


# ----------------------------------------------------------------------
# Matrix coverage: a knob without a registered invariant fails CI
# ----------------------------------------------------------------------

def test_knob_coverage_clean_and_fails_on_new_field():
    assert reg.check_knob_coverage() == []
    fields = [f.name for f in dataclasses.fields(MoEConfig)]
    violations = reg.check_knob_coverage(
        field_names=fields + ["shiny_new_knob"])
    assert [v.subject for v in violations] == ["shiny_new_knob"]
    assert "KnobSpec" in violations[0].detail
    # and a stale registry row (knob removed from the config) is
    # flagged from the other side
    gone = [n for n in fields if n != "a2a_chunks"]
    violations = reg.check_knob_coverage(field_names=gone)
    assert [v.subject for v in violations] == ["a2a_chunks"]


# ----------------------------------------------------------------------
# Decision-name registry runtime behavior
# ----------------------------------------------------------------------

def test_decision_registry_warns_on_unregistered():
    from flashmoe_tpu.utils.telemetry import Metrics

    m = Metrics()
    with pytest.warns(RuntimeWarning, match="unregistered decision"):
        rec = m.decision("made.up_name", x=1)  # staticcheck: ok planted
    assert rec["decision"] == "made.up_name"  # recorded anyway
    assert m.counters["decision.unregistered"] == 1
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        m.decision("planner.drift", path="x")  # registered: no warning
    assert m.counters["decision.unregistered"] == 1


def test_decision_table_matches_doc():
    import os

    from flashmoe_tpu.utils.telemetry import (
        DECISION_NAMES, decision_table_markdown, register_decision,
    )

    table = decision_table_markdown()
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "OBSERVABILITY.md")).read()
    for name in DECISION_NAMES:
        assert f"`{name}`" in table and f"`{name}`" in doc
    # runtime registration extends the registry (plugins); clean up
    register_decision("test.extension", "scratch")
    try:
        assert "test.extension" in DECISION_NAMES
    finally:
        del DECISION_NAMES["test.extension"]


def test_planted_span_name_violations(tmp_path):
    """The span-registry lint (PR 8): a typo'd trace_span literal, an
    f-string with an unregistered base, and a wholly computed span name
    all trip; registered literals — chunk suffix included — pass."""
    from flashmoe_tpu.staticcheck.lint import check_span_names

    bad = tmp_path / "bad_span.py"
    bad.write_text(
        "from flashmoe_tpu.utils.telemetry import trace_span\n"
        "def f(ck, name):\n"
        '    with trace_span("moe.gaet"):\n'        # typo
        "        pass\n"
        '    with trace_span(f"moe.exprt.{ck}"):\n'  # typo'd f-base
        "        pass\n"
        "    with trace_span(name):\n"               # computed
        "        pass\n"
        '    with trace_span("moe.gate"):\n'         # ok
        "        pass\n"
        '    with trace_span(f"moe.expert.{ck}"):\n'  # ok (chunk)
        "        pass\n"
        '    with trace_span("moe.expert.3"):\n'     # ok (suffix)
        "        pass\n")
    violations = check_span_names([str(bad)])
    assert len(violations) == 3
    assert all(v.rule == "span-name" for v in violations)
    details = " | ".join(v.detail for v in violations)
    assert "moe.gaet" in details
    assert "moe.exprt" in details
    assert "non-literal" in details
    # the rule rides run_lint's explicit-paths mode too
    assert sum(1 for v in run_lint(paths=[str(bad)])
               if v.rule == "span-name") == 3


def test_planted_section_literal_typo(tmp_path):
    from flashmoe_tpu.staticcheck.lint import check_span_names

    bad = tmp_path / "bad_section.py"
    bad.write_text(
        "from flashmoe_tpu.profiler import spans as prof\n"
        "def g(i):\n"
        '    with prof.section("train.stpe", step=i):\n'
        "        pass\n"
        '    with prof.section("train.step", step=i):\n'
        "        pass\n")
    violations = check_span_names([str(bad)])
    assert len(violations) == 1
    assert "train.stpe" in violations[0].detail


def test_span_table_matches_doc():
    import os

    from flashmoe_tpu.staticcheck.lint import check_span_doc_sync
    from flashmoe_tpu.utils.telemetry import (
        SPAN_NAMES, register_span, span_table_markdown,
    )

    table = span_table_markdown()
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "OBSERVABILITY.md")).read()
    for name in SPAN_NAMES:
        assert f"`{name}`" in table and f"`{name}`" in doc
    assert check_span_doc_sync() == []
    register_span("test.span_extension", "scratch")
    try:
        assert "test.span_extension" in SPAN_NAMES
    finally:
        del SPAN_NAMES["test.span_extension"]
