"""Live telemetry plane (flashmoe_tpu/telemetry_plane/): quantile
sketch equivalence, exposition-spec compliance, scrape endpoints,
request tracing, shard merge, and the perf-regression sentry.

The CI-shaped acceptance lives here and in tests/test_serving.py
(tracer drill + mid-drill scrape on the real engine); this file covers
the plane's own mechanics plus the planted-regression subprocess gate
(mirroring the staticcheck planted-violation pattern).
"""

import json
import os
import re
import subprocess
import sys
import urllib.request

import pytest

from flashmoe_tpu.telemetry_plane.sketch import (
    EXACT_N, P2Quantile, QuantileSketch, WindowedRate,
)
from flashmoe_tpu.utils.telemetry import (
    Metrics, PROM_CONTENT_TYPE, escape_label_value,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# Streaming quantile sketch
# ----------------------------------------------------------------------

def test_sketch_exact_below_threshold_matches_pctl():
    """Below EXACT_N observations the sketch IS the nearest-rank
    percentile — the loadgen.pctl definition — so every CI-sized drill
    reports identical numbers through either surface."""
    import random

    from flashmoe_tpu.serving.loadgen import pctl

    rng = random.Random(7)
    vals = [rng.uniform(0.5, 200.0) for _ in range(EXACT_N - 1)]
    s = QuantileSketch()
    for v in vals:
        s.observe(v)
    for q in (0.5, 0.9, 0.99):
        assert s.quantile(q) == pytest.approx(pctl(vals, q), abs=1e-3)
    assert s.mean == pytest.approx(sum(vals) / len(vals))
    assert s.summary()["count"] == len(vals)


def test_sketch_p2_error_band_latency_shaped():
    """Beyond the exact buffer, P² estimates stay within the documented
    ~10% relative band on latency-shaped (lognormal) data, and inside
    the observed range by construction."""
    import random

    rng = random.Random(3)
    vals = [rng.lognormvariate(1.0, 0.6) for _ in range(5000)]
    s = QuantileSketch()
    for v in vals:
        s.observe(v)
    exact = sorted(vals)
    for q in (0.5, 0.9, 0.99):
        true = exact[int(q * len(exact))]
        est = s.quantile(q)
        assert min(vals) <= est <= max(vals)
        assert abs(est - true) / true < 0.10, (q, est, true)
    # monotone across tracked quantiles
    assert s.quantile(0.5) <= s.quantile(0.9) <= s.quantile(0.99)


def test_p2_cell_validation_and_tiny_streams():
    with pytest.raises(ValueError, match="quantile"):
        P2Quantile(1.5)
    c = P2Quantile(0.5)
    assert c.value() is None
    for v in (3.0, 1.0):
        c.observe(v)
    assert c.value() in (1.0, 3.0)
    s = QuantileSketch()
    assert s.quantile(0.5) is None and s.summary() == {"count": 0}


def test_windowed_rate_bounded_buckets():
    t = [100.0]
    r = WindowedRate(window_s=10.0, clock=lambda: t[0])
    for _ in range(5):
        r.add(10)
        t[0] += 1.0
    assert r.rate() == pytest.approx(50 / 5.0)
    t[0] += 100.0                      # window empties
    assert r.rate() == 0.0
    # memory stays O(window): thousands of events, few buckets
    for i in range(5000):
        r.add(1)
        t[0] += 0.001
    assert len(r._buckets) <= 12
    with pytest.raises(ValueError):
        WindowedRate(window_s=0)


# ----------------------------------------------------------------------
# Exposition-spec compliance (satellite)
# ----------------------------------------------------------------------

def test_escape_label_value_hostile():
    assert escape_label_value('a"b') == r'a\"b'
    assert escape_label_value("a\nb") == r"a\nb"
    assert escape_label_value("a\\b") == r"a\\b"
    # backslash first: an already-escaped \n must not double-decode
    assert escape_label_value("\\n") == r"\\n"


def test_prometheus_exposition_compliance_hostile_labels():
    """# HELP + # TYPE per family, sketch summaries with quantile
    labels, hostile label values escaped to single parseable lines,
    and the documented content type constant."""
    m = Metrics()
    m.count("steps")
    m.labeled_gauge("build_info", 1.0,
                    host='evil"host\nwith\\stuff', slice="s/0")
    for v in range(100):
        m.sketch("serve.ttft_ms", float(v))
    with m.timer("fwd"):
        pass
    m.histogram("step_ms", 2.0, buckets=(1.0, 5.0))
    text = m.prometheus_text()
    assert PROM_CONTENT_TYPE == "text/plain; version=0.0.4"
    # every family carries HELP and TYPE
    for fam, kind in (("flashmoe_steps_total", "counter"),
                      ("flashmoe_build_info", "gauge"),
                      ("flashmoe_serve_ttft_ms", "summary"),
                      ("flashmoe_fwd_seconds", "summary"),
                      ("flashmoe_step_ms", "histogram")):
        assert f"# TYPE {fam} {kind}" in text
        assert f"# HELP {fam} " in text
    assert r'host="evil\"host\nwith\\stuff"' in text
    assert 'flashmoe_serve_ttft_ms{quantile="0.5"}' in text
    assert "flashmoe_serve_ttft_ms_count 100" in text
    # exposition grammar: one sample per line, no raw newlines leaked
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+-]+$")
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            assert sample.match(line), line


def test_metrics_summary_carries_sketch_stats():
    m = Metrics()
    for v in (1.0, 2.0, 3.0):
        m.sketch("x", v)
    s = m.summary()
    assert s["x_count"] == 3 and s["x_mean"] == pytest.approx(2.0)
    assert "x_p99" in s


# ----------------------------------------------------------------------
# Scrape server
# ----------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode(), r.headers.get("Content-Type")


def test_telemetry_server_endpoints():
    from flashmoe_tpu.telemetry_plane.server import TelemetryServer

    m = Metrics()
    m.gauge("lr", 0.1)
    with TelemetryServer(0, metrics_obj=m,
                         health_fn=lambda: {"queue_depth": 3},
                         vars_fn=lambda: {"plan": ["collective", 1]}) \
            as srv:
        code, body, ctype = _get(f"{srv.url}/metrics")
        assert code == 200 and ctype == PROM_CONTENT_TYPE
        assert "flashmoe_lr" in body
        code, body, _ = _get(f"{srv.url}/healthz")
        hz = json.loads(body)
        assert code == 200 and hz["ok"] is True
        assert hz["queue_depth"] == 3
        code, body, _ = _get(f"{srv.url}/vars")
        assert json.loads(body)["plan"] == ["collective", 1]
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"{srv.url}/nope")
        assert e.value.code == 404
    # start/stop narrate themselves on the served registry
    names = [d["decision"] for d in m.decisions]
    assert names.count("telemetry.server_start") == 1
    assert names.count("telemetry.server_stop") == 1


def test_maybe_server_none_is_off():
    from flashmoe_tpu.telemetry_plane.server import maybe_server

    assert maybe_server(None) is None


def test_host_shard_path_sanitized(tmp_path, monkeypatch):
    from flashmoe_tpu.telemetry_plane.server import host_shard_path

    monkeypatch.setenv("FLASHMOE_HOST_ID", "slice-0/host 1")
    p = host_shard_path(str(tmp_path))
    assert os.path.basename(p) == "telemetry.slice-0_host_1.jsonl"
    assert host_shard_path(str(tmp_path), "h7").endswith(
        "telemetry.h7.jsonl")


# ----------------------------------------------------------------------
# Request tracer mechanics (engine-level drill in test_serving.py)
# ----------------------------------------------------------------------

def _scripted_trace():
    """A hand-driven lifecycle with one eviction, on a fake clock."""
    from flashmoe_tpu.telemetry_plane.tracing import RequestTracer

    t = [0.0]
    m = Metrics()
    tr = RequestTracer(metrics_obj=m, clock=lambda: t[0])

    def span(name, dur):
        tok = tr.span_enter(name)
        t[0] += dur
        tr.span_exit(name, tok)

    tr.on_arrival(7)
    t[0] += 0.002
    tr.begin_step(0, [])
    tr.on_admit(7, 0, resumed=False)
    span("serve.prefill", 0.003)
    span("serve.decode", 0.001)
    tr.end_step()
    tr.begin_step(1, [7])
    span("serve.decode", 0.001)
    tr.on_evict(7, 1)
    tr.end_step()
    t[0] += 0.050                       # the eviction gap
    tr.begin_step(2, [])
    tr.on_admit(7, 2, resumed=True)
    span("serve.prefill", 0.002)
    span("serve.decode", 0.001)
    tr.on_retire(7, 2, tokens=3, ttft_ms=1.0, tpot_ms=0.5)
    tr.end_step()
    return tr, m


def test_tracer_lifecycle_contiguous_with_eviction_gap():
    tr, m = _scripted_trace()
    assert tr.validate() == []
    track = tr.request_track(7)
    names = [s["name"] for s in track]
    assert names[0] == "serve.queued"
    gaps = [s for s in track if s["name"] == "serve.queued"
            and s.get("resumed")]
    assert len(gaps) == 1
    assert gaps[0]["dur_ms"] == pytest.approx(50.0, rel=1e-3)
    st = tr.requests[7]
    assert st.trace_id == "req7-0" and st.evictions == 1
    trace_dec = m.last_decision("serve.trace")
    assert trace_dec["rid"] == 7 and trace_dec["evictions"] == 1
    assert trace_dec["spans"] == len(track)


def test_tracer_validate_catches_orphans_and_holes():
    tr, _ = _scripted_trace()
    # un-covered hole: delete the gap span
    st = tr.requests[7]
    st.spans = [s for s in st.spans
                if not (s["name"] == "serve.queued"
                        and s.get("resumed"))]
    problems = tr.validate()
    assert any("resumed queued spans" in p for p in problems)
    assert any("uncovered gap" in p for p in problems)


def test_tracer_perfetto_export_validates(tmp_path):
    from flashmoe_tpu.profiler.export import (
        request_trace_events, validate_trace, write_request_trace,
    )

    tr, _ = _scripted_trace()
    events = request_trace_events(tr)
    pids = {e["pid"] for e in events}
    assert len(pids) == 1               # one track per request
    assert any(e["name"] == "serve.queued [resumed]" for e in events)
    path = tmp_path / "req.json"
    doc = write_request_trace(tr, str(path))
    assert validate_trace(doc) == []
    assert validate_trace(json.loads(path.read_text())) == []


def test_tracer_chains_to_phase_timeline():
    """The tracer installs OVER an armed PhaseTimeline and forwards —
    phase profiling and request tracing compose."""
    from flashmoe_tpu.profiler import spans as prof
    from flashmoe_tpu.telemetry_plane.tracing import RequestTracer
    from flashmoe_tpu.utils.telemetry import get_span_listener

    tl = prof.PhaseTimeline()
    prof.install(tl)
    try:
        tr = RequestTracer().install()
        assert get_span_listener() is tr
        tl.begin_step(0)
        tr.begin_step(0, [])
        tr.on_admit(1, 0, resumed=False)
        tok = tr.span_enter("serve.prefill")
        tr.span_exit("serve.prefill", tok)
        tr.end_step()
        tl.end_step()
        tr.uninstall()
        assert get_span_listener() is tl
        assert any(s["name"] == "serve.prefill" for s in tl.spans)
        assert any(s["name"] == "serve.prefill"
                   for s in tr.request_track(1))
    finally:
        prof.uninstall()


# ----------------------------------------------------------------------
# observe --trace / --merge
# ----------------------------------------------------------------------

def test_observe_trace_and_merge(tmp_path, capsys):
    from flashmoe_tpu import observe

    tr, _ = _scripted_trace()
    shard = tmp_path / "telemetry.h0.jsonl"
    tr.export_jsonl(str(shard))
    rc = observe.main(["--trace", "7", "--json", str(shard)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip())
    assert rep["found"] and rep["evictions"] == 1
    assert rep["eviction_gap_ms"] == pytest.approx(50.0, rel=1e-3)
    # unknown rid: rc 2 and the known list is named
    assert observe.main(["--trace", "99", str(shard)]) == 2
    assert "traced requests: 7" in capsys.readouterr().out

    shard2 = tmp_path / "telemetry.h1.jsonl"
    shard2.write_text('{"step": 3, "loss": 1.0}\n')
    rc = observe.main(["--merge", "--json", str(shard), str(shard2)])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out.strip())
    assert set(rep["hosts"]) == {"h0", "h1"}
    assert rep["hosts"]["h1"]["records"] == 1
    assert rep["records"] == rep["hosts"]["h0"]["records"] + 1

    # one mode at a time
    with pytest.raises(SystemExit):
        observe.main(["--merge", "--serving", str(shard)])


# ----------------------------------------------------------------------
# Perf-regression sentry
# ----------------------------------------------------------------------

def _run(points, run="r"):
    return {"run": run, "meta": {},
            "metrics": {k: {"value": v, "unit": u}
                        for k, (v, u) in points.items()}}


def test_collect_points_skips_non_measurements():
    from flashmoe_tpu.telemetry_plane import regression as reg

    pts = reg.collect_points([
        {"metric": "a[ms]", "value": 2.0, "unit": "ms",
         "ttft_ms_p50": 4.0},
        {"metric": "skip", "value": None, "skipped": True},
        {"metric": "part", "value": 1.0, "partial": "deadline"},
        {"metric": "err", "value": -1, "error": "boom"},
        {"no_metric": 1},
    ])
    assert set(pts) == {"a[ms]", "a[ms].ttft_ms_p50"}
    assert pts["a[ms].ttft_ms_p50"]["unit"] == "ms"


def test_check_regression_directions_and_decision():
    from flashmoe_tpu.telemetry_plane import regression as reg

    m = Metrics()
    runs = [
        _run({"lat": (10.0, "ms"), "tps": (100.0, "tokens_per_sec")},
             "r1"),
        _run({"lat": (10.0, "ms"), "tps": (100.0, "tokens_per_sec")},
             "r2"),
        # newest: latency +30% (bad), throughput +30% (good)
        _run({"lat": (13.0, "ms"), "tps": (130.0, "tokens_per_sec"),
              "fresh": (1.0, "ms")}, "r3"),
    ]
    rep = reg.check_regression(runs, metrics_obj=m)
    assert [r["metric"] for r in rep["regressions"]] == ["lat"]
    assert [r["metric"] for r in rep["improvements"]] == ["tps"]
    assert rep["new_metrics"] == ["fresh"]
    dec = m.last_decision("regress.detected")
    assert dec["metric"] == "lat" and dec["run"] == "r3"
    # throughput DROP is the regression direction for tokens/s
    runs[-1]["metrics"]["tps"]["value"] = 60.0
    runs[-1]["metrics"]["lat"]["value"] = 10.0
    rep = reg.check_regression(runs, metrics_obj=m)
    assert [r["metric"] for r in rep["regressions"]] == ["tps"]
    # single run: nothing to compare, never a false alarm
    assert reg.check_regression(runs[:1])["regressions"] == []


def _observe_regression(path, *flags):
    return subprocess.run(
        [sys.executable, "-m", "flashmoe_tpu.observe", "--regression",
         *flags, str(path)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_sentry_ci_gate_planted_vs_clean(tmp_path):
    """The CI fixture (satellite): a planted-regression history exits
    rc 2 with the offending metric named; a clean history exits rc 0 —
    subprocess-tested like the staticcheck planted violations."""
    from flashmoe_tpu.telemetry_plane import regression as reg

    clean = tmp_path / "clean.jsonl"
    for run in ("a", "b", "c"):
        reg.append_run(str(clean), {"m[ms]": {"value": 5.0,
                                              "unit": "ms"}}, run=run)
    planted = tmp_path / "planted.jsonl"
    planted.write_text(clean.read_text())
    reg.append_run(str(planted),
                   {"m[ms]": {"value": 9.0, "unit": "ms"}},
                   run="regressed")

    r = _observe_regression(planted, "--ci")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "m[ms]" in r.stdout and "REGRESSED" in r.stdout
    r = _observe_regression(clean, "--ci")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 regression(s)" in r.stdout
    # missing history is an error, not a silent pass
    r = _observe_regression(tmp_path / "absent.jsonl", "--ci")
    assert r.returncode == 2


def test_committed_baseline_seed_passes_ci():
    """The recorded obs/history.jsonl (the baseline seed: deterministic
    golden-config model points) must load, compare, and pass."""
    from flashmoe_tpu.telemetry_plane import regression as reg

    path = os.path.join(REPO, "obs", "history.jsonl")
    runs = reg.load_history(path)
    assert len(runs) >= 2
    assert any(k.startswith("planner_predicted_ms[reference")
               for k in runs[-1]["metrics"])
    rep = reg.check_regression(runs, metrics_obj=Metrics())
    assert rep["compared"] >= 3
    assert rep["regressions"] == []


def test_reference_points_deterministic():
    from flashmoe_tpu.telemetry_plane import regression as reg

    a, b = reg.reference_points(), reg.reference_points()
    assert a == b and len(a) >= 3
    assert all(v["unit"] in ("ms", "hidden_frac", "frac",
                             "accept_rate", "tokens_per_step")
               and v["value"] > 0 for v in a.values())
    # the measured-latency plane rides along (PR 17): a virtual-clock
    # TTFT and a hidden-fraction point per golden config
    assert any(k.startswith("fabric_ttft_vclock_ms[") and
               v["unit"] == "ms" for k, v in a.items())
    assert any(k.startswith("fabric_handoff_hidden_frac[") and
               v["unit"] == "hidden_frac" and 0 < v["value"] <= 1.0
               for k, v in a.items())
    # PR 18: fault-recovery latency per golden config plus the analytic
    # brownout shed fraction, gating the serving-side failure ladder
    assert any(k.startswith("fabric_recovery_ms[") and
               v["unit"] == "ms" for k, v in a.items())
    shed = a["fabric_shed_frac[brownout,reference]"]
    assert shed["unit"] == "frac" and 0 < shed["value"] < 1.0
    # ISSUE 20: the speculative-decode plane — a modeled break-even
    # acceptance and an expected-tokens-per-verify-step point per
    # golden config
    assert any(k.startswith("decode_accept_rate[") and
               v["unit"] == "accept_rate" and 0 < v["value"] < 1.0
               for k, v in a.items())
    assert any(k.startswith("spec_tokens_per_step[") and
               v["unit"] == "tokens_per_step" and v["value"] > 1.0
               for k, v in a.items())


def test_check_regression_zero_baseline_direction_aware():
    """A recovery from a 0-baseline throughput run is an improvement,
    not a regression (code-review finding: the directions used to
    cancel), and the report stays JSON-serializable (no Infinity)."""
    from flashmoe_tpu.telemetry_plane import regression as reg

    runs = [_run({"tps": (0.0, "tokens_per_sec"),
                  "lat": (0.0, "ms")}, "dead"),
            _run({"tps": (120.0, "tokens_per_sec"),
                  "lat": (5.0, "ms")}, "alive")]
    rep = reg.check_regression(runs, metrics_obj=Metrics())
    assert [r["metric"] for r in rep["improvements"]] == ["tps"]
    # latency OFF a zero baseline is the bad direction
    assert [r["metric"] for r in rep["regressions"]] == ["lat"]
    json.dumps(rep)    # finite sentinel: valid JSON end to end


def test_tracer_evictee_leaves_step_window():
    """An evicted request stops riding the step at the eviction
    instant (code-review finding): no serve.decode span lands after
    its eviction, and its serve.step span ends where the eviction gap
    opens — decode slices never overlap the visible gap."""
    from flashmoe_tpu.telemetry_plane.tracing import RequestTracer

    t = [0.0]
    tr = RequestTracer(metrics_obj=Metrics(), clock=lambda: t[0])

    def span(name, dur):
        tok = tr.span_enter(name)
        t[0] += dur
        tr.span_exit(name, tok)

    tr.on_arrival(1)
    tr.begin_step(0, [])
    tr.on_admit(1, 0, resumed=False)
    span("serve.prefill", 0.002)
    span("serve.decode", 0.001)
    tr.end_step()
    tr.begin_step(1, [1])
    t[0] += 0.001
    tr.on_evict(1, 1)              # evicted BEFORE this step's decode
    evict_ms = t[0] * 1e3
    span("serve.decode", 0.005)    # the survivors' decode
    tr.end_step()
    track = tr.request_track(1)
    step1 = [s for s in track if s["name"] == "serve.step"
             and s["step"] == 1]
    assert len(step1) == 1
    assert step1[0]["ts_ms"] + step1[0]["dur_ms"] == \
        pytest.approx(evict_ms, abs=1e-6)
    decodes_step1 = [s for s in track if s["name"] == "serve.decode"
                     and s["step"] == 1]
    assert decodes_step1 == []     # the post-evict decode is not ours
    assert tr.requests[1].open_queued == pytest.approx(evict_ms)
