"""End-to-end training CLI as a subprocess."""

import json
import os
import subprocess
import sys

import pytest


def _run(args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, "-m", "flashmoe_tpu.runtime.train_cli", *args],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=__import__("pathlib").Path(__file__).parent.parent,
    )


SMALL = ["--steps", "2", "--batch", "2",
         "--set", "sequence_len=32", "--set", "hidden_size=64",
         "--set", "intermediate_size=128", "--set", "vocab_size=256",
         "--set", "num_heads=2", "--set", "num_layers=1",
         "--set", "moe_frequency=1", "--set", "num_experts=4",
         "--set", "dtype=float32", "--set", "param_dtype=float32"]


def test_synthetic_training(devices):
    out = _run(SMALL + ["--synthetic"])
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["steps"] == 2
    assert rec["final_loss"] is not None


@pytest.mark.slow
def test_with_data_and_checkpointing(devices, tmp_path):
    import numpy as np
    from flashmoe_tpu.runtime.data import write_token_file
    data = tmp_path / "toks.bin"
    write_token_file(str(data), np.arange(33 * 8, dtype=np.int32) % 256)
    ck = tmp_path / "ck"
    out = _run(SMALL + ["--data", str(data), "--checkpoint-dir", str(ck),
                        "--checkpoint-every", "1"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert (ck / "2").exists()  # checkpoint at final step


@pytest.mark.slow
def test_sigterm_drains_and_resumes(devices, tmp_path):
    """The real preemption path: SIGTERM to the CLI drains a final
    checkpoint + loader cursor inside the grace window, exits 0, and a
    re-run resumes from the drained step (docs/RESILIENCE.md)."""
    import signal
    import time

    import numpy as np
    from flashmoe_tpu.runtime.data import write_token_file

    data = tmp_path / "toks.bin"
    write_token_file(str(data), np.arange(33 * 8, dtype=np.int32) % 256)
    ck = tmp_path / "ck"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    base = SMALL[2:]  # SMALL minus its ["--steps", "2"] prefix
    proc = subprocess.Popen(
        [sys.executable, "-m", "flashmoe_tpu.runtime.train_cli",
         "--steps", "500", *base, "--data", str(data),
         "--checkpoint-dir", str(ck),
         "--checkpoint-every", "3", "--async-save"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
        cwd=__import__("pathlib").Path(__file__).parent.parent)
    try:
        # wait for the first periodic checkpoint, then preempt
        deadline = time.time() + 300
        while time.time() < deadline:
            if (ck / "3").exists():
                break
            time.sleep(0.5)
        else:
            proc.kill()
            pytest.fail("no checkpoint appeared before the deadline")
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise
    assert proc.returncode == 0, err[-2000:]
    assert "preempted: drained at step" in err

    from flashmoe_tpu.runtime import checkpoint as ckpt

    drained = ckpt.latest_step(str(ck))
    assert drained is not None and drained >= 3
    assert ckpt.verify(str(ck), drained)
    ls = ckpt.load_loader_state(str(ck), drained)
    assert ls is not None and ls["epoch"] * 8 + ls["cursor"] == 2 * drained

    # the re-run resumes from the drained step (few steps left)
    out2 = _run(["--steps", str(drained + 2), *base,
                 "--data", str(data), "--checkpoint-dir", str(ck),
                 "--checkpoint-every", "3"], timeout=420)
    assert out2.returncode == 0, out2.stderr[-2000:]
    rec = json.loads(out2.stdout.strip().splitlines()[-1])
    assert rec.get("resumes") == 1.0
