"""End-to-end training CLI as a subprocess."""

import json
import os
import subprocess
import sys

import pytest


def _run(args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(
        [sys.executable, "-m", "flashmoe_tpu.runtime.train_cli", *args],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=__import__("pathlib").Path(__file__).parent.parent,
    )


SMALL = ["--steps", "2", "--batch", "2",
         "--set", "sequence_len=32", "--set", "hidden_size=64",
         "--set", "intermediate_size=128", "--set", "vocab_size=256",
         "--set", "num_heads=2", "--set", "num_layers=1",
         "--set", "moe_frequency=1", "--set", "num_experts=4",
         "--set", "dtype=float32", "--set", "param_dtype=float32"]


def test_synthetic_training(devices):
    out = _run(SMALL + ["--synthetic"])
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["steps"] == 2
    assert rec["final_loss"] is not None


@pytest.mark.slow
def test_with_data_and_checkpointing(devices, tmp_path):
    import numpy as np
    from flashmoe_tpu.runtime.data import write_token_file
    data = tmp_path / "toks.bin"
    write_token_file(str(data), np.arange(33 * 8, dtype=np.int32) % 256)
    ck = tmp_path / "ck"
    out = _run(SMALL + ["--data", str(data), "--checkpoint-dir", str(ck),
                        "--checkpoint-every", "1"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert (ck / "2").exists()  # checkpoint at final step
