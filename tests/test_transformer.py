"""Flagship transformer: forward, loss, sharded training on the mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.transformer import (
    forward, init_params, loss_fn, sgd_train_step,
)
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime.trainer import (
    init_state, make_optimizer, make_train_step, state_shardings, train,
)

CFG = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=128,
                intermediate_size=256, sequence_len=64, num_layers=2,
                moe_frequency=2, vocab_size=512, num_heads=4,
                drop_tokens=False, is_training=True, ep=4,
                dtype=jnp.float32, param_dtype=jnp.float32)


def _batch(cfg, b=2, seed=0):
    return {
        "tokens": jax.random.randint(
            jax.random.PRNGKey(seed), (b, cfg.sequence_len + 1), 0,
            cfg.vocab_size
        )
    }


def test_forward_shapes():
    cfg = CFG.replace(ep=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = _batch(cfg)["tokens"][:, :-1]
    logits, aux = forward(params, tokens, cfg)
    assert logits.shape == (2, cfg.sequence_len, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0  # MoE layer contributes aux loss


def test_dense_layers_interleave():
    """moe_frequency=2 -> layer 0 dense (1 expert), layer 1 MoE."""
    cfg = CFG.replace(ep=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["layers"][0]["moe"]["w_up"].shape[0] == 1
    assert params["layers"][1]["moe"]["w_up"].shape[0] == cfg.num_experts


@pytest.mark.slow
def test_train_step_decreases_loss(devices):
    mesh = make_mesh(CFG)
    params = init_params(jax.random.PRNGKey(0), CFG)
    batch = _batch(CFG)
    p1, l1, m1 = sgd_train_step(params, batch, CFG, lr=1e-2, mesh=mesh)
    p2, l2, m2 = sgd_train_step(p1, batch, CFG, lr=1e-2, mesh=mesh)
    assert float(l2) < float(l1)
    assert np.isfinite(float(m2["ce"]))


def test_optax_trainer_with_shardings(devices):
    mesh = make_mesh(CFG)
    opt = make_optimizer(CFG, total_steps=4)
    state = init_state(jax.random.PRNGKey(0), CFG, opt)
    state = jax.device_put(state, state_shardings(state, CFG, mesh))
    step = make_train_step(CFG, mesh, opt)
    batch = _batch(CFG)
    losses = []
    for _ in range(3):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 3
    assert losses[-1] < losses[0]
    # expert weights actually sharded over ep
    moe_w = state.params["layers"][1]["moe"]["w_up"]
    assert "ep" in str(moe_w.sharding.spec) or moe_w.sharding.is_fully_replicated is False


@pytest.mark.parametrize("backend", ["fused", "ragged"])
@pytest.mark.slow
def test_moe_backend_selection(backend, devices):
    """The flagship model can route its distributed MoE through the fused
    RDMA kernel or the dropless ragged layer and still match the default
    collective path (forward AND gradients)."""
    cfg = CFG.replace(ep=2, moe_backend=backend, moe_frequency=1,
                      num_layers=1)
    mesh = make_mesh(cfg, devices=devices[:2], dp=1)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = _batch(cfg)

    def loss_with(backend_name):
        c = cfg.replace(moe_backend=backend_name)
        return float(jax.jit(
            lambda p, b: loss_fn(p, b, c, mesh, False)[0]
        )(params, batch))

    lb = loss_with(backend)
    lc = loss_with("collective")
    np.testing.assert_allclose(lb, lc, rtol=2e-4)

    def grads_with(backend_name):
        c = cfg.replace(moe_backend=backend_name)
        return jax.jit(jax.grad(
            lambda p: loss_fn(p, batch, c, mesh, False)[0]
        ))(params)

    gb = grads_with(backend)
    gc = grads_with("collective")
    fb, _ = jax.tree_util.tree_flatten_with_path(gb)
    fc, _ = jax.tree_util.tree_flatten_with_path(gc)
    for (path, a), (_, b) in zip(fb, fc):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3,
            err_msg=jax.tree_util.keystr(path),
        )


@pytest.mark.slow
def test_sequence_parallel_forward(devices):
    """sp=2: ring attention + EP MoE with tokens sharded over (ep, sp)."""
    cfg = CFG.replace(ep=2, sp=2, sequence_len=128)
    mesh = make_mesh(cfg)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = _batch(cfg)["tokens"][:, :-1]
    logits, aux = forward(params, tokens, cfg, mesh)
    # oracle: same params, no mesh (single-device dense path)
    want, _ = forward(params, tokens, cfg.replace(ep=1, sp=1), None)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(want), rtol=2e-3, atol=2e-3
    )


def test_train_loop_helper(devices):
    mesh = make_mesh(CFG)
    it = iter([_batch(CFG, seed=i) for i in range(3)])
    state, hist = train(CFG, mesh, it, num_steps=3, log_every=1)
    assert len(hist) == 3
    assert all(np.isfinite(h["loss"]) for h in hist)
