"""Per-generation tuning table (the reference's arch trait table,
``csrc/include/flashmoe/arch.cuh:95-222``, as measured data instead of
hardcoded constexprs)."""

import json

import jax.numpy as jnp
import pytest

from flashmoe_tpu import tuning
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.ops.expert import _capacity_tiling


@pytest.fixture(autouse=True)
def _clean_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("FLASHMOE_TUNING_FILE",
                       str(tmp_path / "missing.json"))
    tuning._load.cache_clear()
    yield
    tuning._load.cache_clear()


def test_lookup_empty_without_table():
    assert tuning.lookup("capacity_ffn", h=2048, i=2048,
                         dtype="bfloat16") == {}


def test_save_load_roundtrip_and_match(tmp_path, monkeypatch):
    path = str(tmp_path / "v5e.json")
    entries = [{"kernel": "capacity_ffn",
                "match": {"h": 2048, "i": 2048, "dtype": "bfloat16"},
                "set": {"block_m": 256, "block_i": 512},
                "measured_ms": 1.0}]
    tuning.save_entries("v5e", entries, path=path)
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", path)
    tuning._load.cache_clear()
    got = tuning.lookup("capacity_ffn", h=2048, i=2048, dtype="bfloat16")
    assert got == {"block_m": 256, "block_i": 512}
    # a different shape falls through to {}
    assert tuning.lookup("capacity_ffn", h=1024, i=2048,
                         dtype="bfloat16") == {}
    # re-saving the same key replaces, not duplicates
    entries[0]["set"] = {"block_m": 128, "block_i": 256}
    tuning.save_entries("v5e", entries, path=path)
    with open(path) as f:
        assert len(json.load(f)["entries"]) == 1
    tuning._load.cache_clear()
    assert tuning.lookup("capacity_ffn", h=2048, i=2048,
                         dtype="bfloat16")["block_m"] == 128


def test_committed_tables_pass_schema_validation():
    """ISSUE 12 satellite: every table committed under tuning_data/
    must validate — a malformed entry fails CI here instead of being
    silently ignored by the lenient runtime loader."""
    import glob
    import os

    data_dir = os.path.join(os.path.dirname(tuning.__file__),
                            "tuning_data")
    for path in glob.glob(os.path.join(data_dir, "*.json")):
        assert tuning.validate_table(path) == [], path


def test_schema_validation_catches_malformed_entries(tmp_path):
    ok = {"generation": "v5e", "entries": [
        {"kernel": "fused_tiles",
         "match": {"h": 4096, "i": 14336, "dtype": "bfloat16"},
         "set": {"cm": 32, "kw": 256}, "measured_ms": 3.1},
        {"kernel": "fused_ep", "match": {"h": 2048},
         "set": {"cm": 256, "rowwin": True}},
        {"kernel": "path_latency",
         "match": {"path": "fused", "h": 2048, "d": 8},
         "measured_ms": 2.71},
        {"kernel": "path_latency",
         "match": {"path": "collective", "h": 2048, "spec": "v3"},
         "measured_ms": 3.4},       # speculative verify span (ISSUE 20)
    ]}
    assert tuning.validate_entries(ok) == []

    def bad(entry):
        return tuning.validate_entries({"entries": [entry]})

    assert bad({"kernel": "fuzed_ep", "match": {}, "set": {"cm": 1}})
    assert bad({"kernel": "fused_ep", "match": {"hh": 2048},
                "set": {"cm": 256}})                  # unknown match key
    assert bad({"kernel": "fused_ep", "match": {},
                "set": {"cmm": 256}})                 # misspelled knob
    assert bad({"kernel": "fused_tiles", "match": {},
                "set": {"cm": 32}})                   # half-specified pair
    assert bad({"kernel": "fused_tiles", "match": {},
                "set": {"cm": 32, "kw": "wide"}})     # non-int knob
    assert bad({"kernel": "path_latency",
                "match": {"h": 2048}, "measured_ms": 2.0})  # no path
    assert bad({"kernel": "path_latency",
                "match": {"path": "fused"},
                "measured_ms": "fast"})               # non-numeric ms
    assert bad({"kernel": "path_latency",
                "match": {"path": "fused", "spec": 3},
                "measured_ms": 2.0})                  # spec tag not str
    assert bad({"kernel": "fused_ep", "match": {"h": 2048}})  # no set
    assert tuning.validate_entries({"entries": "nope"})
    assert tuning.validate_entries([])                # not an object
    # CI-facing file validator reports unreadable files as problems
    p = tmp_path / "corrupt.json"
    p.write_text("{not json")
    assert tuning.validate_table(str(p))


def test_fused_tiles_entry_overrides_rowwin_chooser(tmp_path,
                                                    monkeypatch):
    """A measured fused_tiles entry overrides the IO-aware analytic
    pick when it divides the shapes; a non-dividing or VMEM-infeasible
    entry is discarded (the budget gate is never overridable)."""
    from flashmoe_tpu.parallel.fused import _rowwin_tiles

    analytic = _rowwin_tiles(256, 2048, 2048, 2, "bfloat16", False,
                             False, 2)
    assert analytic[0] is not None
    path = str(tmp_path / "v5e.json")
    tuning.save_entries("v5e", [{
        "kernel": "fused_tiles",
        "match": {"h": 2048, "i": 2048, "dtype": "bfloat16"},
        "set": {"cm": 32, "kw": 128},
    }], path=path)
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", path)
    tuning._load.cache_clear()
    assert _rowwin_tiles(256, 2048, 2048, 2, "bfloat16", False,
                         False, 2) == (32, 128)
    # a pair that stopped dividing the capacity falls back to analytic
    cm, kw = _rowwin_tiles(48, 2048, 2048, 2, "bfloat16", False,
                           False, 2)
    assert 48 % cm == 0 and cm != 32
    # an entry past the VMEM budget is likewise ignored
    tuning.save_entries("v5e", [{
        "kernel": "fused_tiles",
        "match": {"h": 2048, "i": 2048, "dtype": "bfloat16"},
        "set": {"cm": 256, "kw": 2048},
    }], path=path)
    tuning._load.cache_clear()
    assert _rowwin_tiles(256, 2048, 2048, 2, "bfloat16", False,
                         False, 2) == analytic


def test_capacity_tiling_consults_table(tmp_path, monkeypatch):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=2048,
                    intermediate_size=2048, dtype=jnp.bfloat16,
                    param_dtype=jnp.float32)
    bm_h, cp_h, bi_h = _capacity_tiling(1024, cfg)  # heuristic (no table)
    path = str(tmp_path / "v5e.json")
    tuning.save_entries("v5e", [{
        "kernel": "capacity_ffn",
        "match": {"h": 2048, "i": 2048, "dtype": "bfloat16"},
        "set": {"block_m": 128, "block_i": 256},
    }], path=path)
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", path)
    tuning._load.cache_clear()
    bm, cp, bi = _capacity_tiling(1024, cfg)
    assert (bm, bi) == (128, 256)
    assert cp % bm == 0 and cp >= 1024
    # no cfg -> pure heuristic, table untouched
    assert _capacity_tiling(1024)[0] == bm_h
