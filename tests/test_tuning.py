"""Per-generation tuning table (the reference's arch trait table,
``csrc/include/flashmoe/arch.cuh:95-222``, as measured data instead of
hardcoded constexprs)."""

import json

import jax.numpy as jnp
import pytest

from flashmoe_tpu import tuning
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.ops.expert import _capacity_tiling


@pytest.fixture(autouse=True)
def _clean_cache(monkeypatch, tmp_path):
    monkeypatch.setenv("FLASHMOE_TUNING_FILE",
                       str(tmp_path / "missing.json"))
    tuning._load.cache_clear()
    yield
    tuning._load.cache_clear()


def test_lookup_empty_without_table():
    assert tuning.lookup("capacity_ffn", h=2048, i=2048,
                         dtype="bfloat16") == {}


def test_save_load_roundtrip_and_match(tmp_path, monkeypatch):
    path = str(tmp_path / "v5e.json")
    entries = [{"kernel": "capacity_ffn",
                "match": {"h": 2048, "i": 2048, "dtype": "bfloat16"},
                "set": {"block_m": 256, "block_i": 512},
                "measured_ms": 1.0}]
    tuning.save_entries("v5e", entries, path=path)
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", path)
    tuning._load.cache_clear()
    got = tuning.lookup("capacity_ffn", h=2048, i=2048, dtype="bfloat16")
    assert got == {"block_m": 256, "block_i": 512}
    # a different shape falls through to {}
    assert tuning.lookup("capacity_ffn", h=1024, i=2048,
                         dtype="bfloat16") == {}
    # re-saving the same key replaces, not duplicates
    entries[0]["set"] = {"block_m": 128, "block_i": 256}
    tuning.save_entries("v5e", entries, path=path)
    with open(path) as f:
        assert len(json.load(f)["entries"]) == 1
    tuning._load.cache_clear()
    assert tuning.lookup("capacity_ffn", h=2048, i=2048,
                         dtype="bfloat16")["block_m"] == 128


def test_capacity_tiling_consults_table(tmp_path, monkeypatch):
    cfg = MoEConfig(num_experts=8, expert_top_k=2, hidden_size=2048,
                    intermediate_size=2048, dtype=jnp.bfloat16,
                    param_dtype=jnp.float32)
    bm_h, cp_h, bi_h = _capacity_tiling(1024, cfg)  # heuristic (no table)
    path = str(tmp_path / "v5e.json")
    tuning.save_entries("v5e", [{
        "kernel": "capacity_ffn",
        "match": {"h": 2048, "i": 2048, "dtype": "bfloat16"},
        "set": {"block_m": 128, "block_i": 256},
    }], path=path)
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", path)
    tuning._load.cache_clear()
    bm, cp, bi = _capacity_tiling(1024, cfg)
    assert (bm, bi) == (128, 256)
    assert cp % bm == 0 and cp >= 1024
    # no cfg -> pure heuristic, table untouched
    assert _capacity_tiling(1024)[0] == bm_h
