"""Telemetry metrics + throughput probe."""

import json

import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.runtime.throughput import measure_expert_throughput
from flashmoe_tpu.utils.telemetry import Metrics, trace_span


def test_metrics_registry(tmp_path):
    m = Metrics()
    m.count("steps")
    m.count("steps")
    m.gauge("lr", 3e-4)
    with m.timer("fwd"):
        pass
    s = m.summary()
    assert s["steps"] == 2
    assert s["lr"] == 3e-4
    assert "fwd_ms_p50" in s and s["fwd_calls"] == 1
    rec = m.dump_jsonl(str(tmp_path / "m.jsonl"), rank=0)
    assert rec["rank"] == 0
    line = json.loads((tmp_path / "m.jsonl").read_text().strip())
    assert line["steps"] == 2


def test_trace_span_noop():
    with trace_span("unit-test"):  # staticcheck: ok deliberately unregistered no-op span
        x = jnp.ones((4, 4)).sum()
    assert float(x) == 16.0


def test_throughput_probe_cached():
    cfg = MoEConfig(num_experts=4, hidden_size=128, intermediate_size=256,
                    dtype=jnp.float32, param_dtype=jnp.float32)
    t1 = measure_expert_throughput(cfg, experts=2, rows_per_expert=32,
                                   chain=2, trials=1)
    assert t1 > 0
    t2 = measure_expert_throughput(cfg, experts=2, rows_per_expert=32,
                                   chain=2, trials=1)
    assert t1 == t2  # cache hit
