"""Wire-dtype EP payload compression (ops/wire.py + the ep/ragged_ep
transports): codec properties, bit-identical-when-off guarantees,
hierarchical round trips, planner/tuning keying, and the bf16-wire
training smoke."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params, reference_moe
from flashmoe_tpu.ops import wire as wr
from flashmoe_tpu.parallel.ep import ep_moe_layer
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

F32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)

WIRES = ["bf16", "e4m3", "e5m2"]


# ----------------------------------------------------------------------
# Codec properties
# ----------------------------------------------------------------------

def _rows(seed=0, shape=(32, 64), scale=3.0):
    return jax.random.normal(jax.random.PRNGKey(seed), shape,
                             jnp.float32) * scale


@pytest.mark.parametrize("name", WIRES)
def test_roundtrip_accuracy(name):
    x = _rows()
    wd = wr.resolve(name)
    rt = wr.roundtrip(x, wd)
    err = float(wr.roundtrip_error(x, wd))
    # bf16 keeps ~8 mantissa bits, e4m3 3, e5m2 2
    bound = {"bf16": 0.005, "e4m3": 0.04, "e5m2": 0.08}[name]
    assert 0 < err < bound
    assert np.isfinite(np.asarray(rt)).all()


@pytest.mark.parametrize("name", WIRES)
def test_zero_preserving(name):
    wd = wr.resolve(name)
    # all-zero rows survive exactly (scale falls back to 1.0) ...
    z = jnp.zeros((4, 16), jnp.float32)
    np.testing.assert_array_equal(np.asarray(wr.roundtrip(z, wd)), 0.0)
    # ... and zero ELEMENTS inside nonzero rows stay exactly zero
    x = _rows(1).at[:, ::3].set(0.0)
    rt = np.asarray(wr.roundtrip(x, wd))
    np.testing.assert_array_equal(rt[:, ::3], 0.0)


@pytest.mark.parametrize("name", ["e4m3", "e5m2"])
def test_scale_monotone(name):
    """Scaling a row by c > 0 leaves the fp8 payload bit-identical and
    scales the sidecar (and therefore the decoded row) by exactly c —
    the quantization grid rides the row's amax."""
    wd = wr.resolve(name)
    x = _rows(2)
    p1, s1 = wr.encode(x, wd)
    for c in (0.25, 4.0):  # powers of two: exact f32 scaling
        p2, s2 = wr.encode(x * c, wd)
        np.testing.assert_array_equal(np.asarray(p1).view(np.uint8),
                                      np.asarray(p2).view(np.uint8))
        np.testing.assert_array_equal(np.asarray(s2), np.asarray(s1) * c)
        np.testing.assert_array_equal(
            np.asarray(wr.decode(p2, s2, jnp.float32)),
            np.asarray(wr.decode(p1, s1, jnp.float32)) * c)


@pytest.mark.parametrize("name", WIRES)
@pytest.mark.parametrize("bad", [jnp.nan, jnp.inf, -jnp.inf])
def test_nonfinite_propagates_through_wire(name, bad):
    """A poisoned row must decode non-finite (the tier-0 health mask
    fires on the far side); clean rows in the same batch stay finite."""
    wd = wr.resolve(name)
    x = _rows(3, shape=(8, 32)).at[2, 5].set(bad)
    rt = np.asarray(wr.roundtrip(x, wd))
    assert not np.isfinite(rt[2]).all()
    clean = np.delete(rt, 2, axis=0)
    assert np.isfinite(clean).all()


def test_wire_names_and_errors():
    assert wr.canonical_name(None) == "off"
    assert wr.canonical_name("bfloat16") == "bf16"
    assert wr.canonical_name("fp8") == "e4m3"
    assert wr.canonical_name("float8_e5m2") == "e5m2"
    assert wr.resolve(None) is None
    assert wr.scale_bytes(wr.resolve("e4m3")) == 4
    assert wr.scale_bytes(wr.resolve("bf16")) == 0
    with pytest.raises(ValueError, match="unknown wire dtype"):
        wr.resolve("int4")


# ----------------------------------------------------------------------
# Config validation (satellite: fail at config time, not in shard_map)
# ----------------------------------------------------------------------

def test_config_rejects_unsupported_combinations():
    with pytest.raises(ValueError, match="unknown wire dtype"):
        MoEConfig(wire_dtype="float7")
    with pytest.raises(ValueError, match="fused"):
        MoEConfig(wire_dtype="bf16", moe_backend="fused", **F32)
    with pytest.raises(ValueError, match="fused"):
        MoEConfig(wire_dtype_combine="e4m3", moe_backend="fused", **F32)
    with pytest.raises(ValueError, match="wider"):
        MoEConfig(dtype=jnp.float8_e4m3fn, wire_dtype="bf16")
    # valid combos construct (and are hashable for jit static args)
    hash(MoEConfig(wire_dtype="e4m3", wire_dtype_combine="bf16", **F32))
    hash(MoEConfig(wire_dtype="bf16", moe_backend="auto", **F32))


# ----------------------------------------------------------------------
# EP layers: off = bit-identical, on = accurate
# ----------------------------------------------------------------------

def _ep_setup(ep=2, **over):
    # ep=2 keeps the virtual-mesh compiles inside the tier-1 budget;
    # the hierarchical test builds its own ep=4 point
    base = dict(num_experts=8, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=64 * ep,
                drop_tokens=False, ep=ep, **F32)
    base.update(over)
    cfg = MoEConfig(**base)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    return cfg, params, x


def test_wire_off_invariants_via_staticcheck(devices):
    """Bit-identical-when-off + fp8-free graphs for BOTH wire knobs
    across every registered EP backend (flat / hierarchical / ragged) —
    delegated to the staticcheck invariant engine, which replaced the
    hand-rolled per-layer jaxpr assertions this file used to carry
    (config identity => one jit cache entry => same bits by
    construction; plus the fp8-present sanity on the on-trace).
    Trace-only: wire-off EXECUTION accuracy is test_ep.py /
    test_ragged_ep.py's existing oracle coverage."""
    from flashmoe_tpu.staticcheck.invariants import run_invariants

    assert run_invariants(knobs=["wire_dtype", "wire_dtype_combine"],
                          devices=devices, include_coverage=False) == []


@pytest.mark.parametrize("wd,wc", [("bf16", None), ("e4m3", "e5m2")])
def test_ep_wire_on_tracks_oracle(wd, wc, devices):
    """Two points cover both codec families and both legs: bf16
    dispatch-only (plain cast), fp8 on both legs (scaled, sidecar) —
    the fp8 point also carries collect_stats so the wire_rtq_error
    proxy is asserted on a compile this test pays for anyway."""
    stats = wc is not None
    cfg, params, x = _ep_setup(collect_stats=stats)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    want, _ = reference_moe(params, x, cfg)
    on = ep_moe_layer(
        params, x, cfg.replace(wire_dtype=wd, wire_dtype_combine=wc),
        mesh)
    scale = float(jnp.max(jnp.abs(want)))
    err = float(jnp.max(jnp.abs(on.out - want))) / scale
    # fp8 keeps 2-3 mantissa bits per leg (e5m2 on the combine leg is
    # the loosest supported combination); the bf16 wire is near-exact
    assert err < (0.01 if (wd, wc) == ("bf16", None) else 0.15)
    assert int(jnp.sum(on.expert_counts)) == cfg.tokens * cfg.expert_top_k
    if stats:
        assert 0.0 < float(on.stats.wire_rtq_error) < 0.1


@pytest.mark.slow
def test_hierarchical_a2a_wire_roundtrip_matches_flat(devices):
    """The two-stage (intra-slice, inter-slice) exchange must carry
    payload AND fp8 scales consistently through both hops: with the wire
    on, hierarchical and flat outputs are bit-identical (same codec,
    same values, different routes)."""
    cfg, params, x = _ep_setup(ep=4)
    on = cfg.replace(wire_dtype="e4m3", wire_dtype_combine="bf16")
    mesh = make_mesh(cfg, dp=1, devices=devices[:4])
    flat = ep_moe_layer(params, x, on, mesh)
    hier = ep_moe_layer(params, x, on, mesh, dcn_inner=2)
    np.testing.assert_array_equal(np.asarray(flat.out),
                                  np.asarray(hier.out))


def test_ragged_wire_on_accurate(devices):
    # Wire-off identity and the fp8-free ragged graph are the invariant
    # engine's job now (test_wire_off_invariants_via_staticcheck covers
    # the ragged backend in the same matrix).  The single expensive
    # compile this test pays for is the wire-ON dense-arm exchange (fp8
    # payload + scale sidecar; the combine-wire variant shares the
    # identical _wired_row_exchange path, exercised on the ep layer
    # above).
    cfg, params, x = _ep_setup(sequence_len=64)
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    want, _ = reference_moe(params, x, cfg)
    on = ragged_ep_moe_layer(
        params, x, cfg.replace(wire_dtype="e4m3"), mesh,
        exchange="dense")
    scale = float(jnp.max(jnp.abs(want)))
    assert float(jnp.max(jnp.abs(on.out - want))) / scale < 0.1


def test_fused_layer_rejects_wire(devices):
    """Direct fused-layer calls must refuse wire knobs rather than
    silently ship raw slabs (config.py already rejects
    moe_backend='fused' + wire at construction)."""
    from flashmoe_tpu.parallel.fused import fused_ep_moe_layer

    cfg, params, x = _ep_setup(ep=2, sequence_len=64, wire_dtype="bf16")
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])
    with pytest.raises(ValueError, match="raw slabs"):
        fused_ep_moe_layer(params, x, cfg, mesh, interpret=True)


def test_wire_stats_zero_when_off_and_in_host_dict():
    """Wire off reports exactly 0.0 error (single-chip layer — same
    MoEStats contract, no mesh compile), and stats_to_host carries the
    field; the wire-ON proxy value is asserted in the hierarchical test
    above, riding its compiles."""
    from flashmoe_tpu.ops.moe import moe_layer
    from flashmoe_tpu.ops.stats import stats_to_host

    cfg, params, x = _ep_setup(ep=1, collect_stats=True)
    off = moe_layer(params, x, cfg, use_pallas=False)
    assert float(off.stats.wire_rtq_error) == 0.0
    assert stats_to_host(off.stats)["wire_rtq_error"] == 0.0


@pytest.mark.slow
def test_ep_wire_grad_finite(devices):
    """Training through an fp8 wire: grads flow (the codec is plain
    cast/scale arithmetic) and stay finite."""
    cfg, params, x = _ep_setup(ep=2, sequence_len=64, is_training=True,
                               wire_dtype="e4m3",
                               wire_dtype_combine="bf16")
    mesh = make_mesh(cfg, dp=1, devices=devices[:2])

    def loss(p):
        o = ep_moe_layer(p, x, cfg, mesh)
        return jnp.sum(o.out.astype(jnp.float32) ** 2) + o.aux_loss

    g = jax.grad(loss)(params)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.isfinite(np.asarray(leaf)).all()


# ----------------------------------------------------------------------
# 50-step CPU smoke train: bf16 wire tracks the f32 baseline
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_smoke_train_bf16_wire_tracks_f32_baseline(devices):
    """Two full 50-step training jobs — slow-marked per the repo's
    convention that full training jobs stay out of the fast gate
    (tests/test_collection.py; ROADMAP tier-1 budget)."""
    from flashmoe_tpu.runtime.trainer import (
        init_state, make_optimizer, make_train_step, state_shardings,
    )

    def run(wire):
        cfg = MoEConfig(num_experts=4, expert_top_k=2, hidden_size=64,
                        intermediate_size=128, sequence_len=32,
                        num_layers=1, moe_frequency=1, vocab_size=256,
                        num_heads=2, drop_tokens=False, is_training=True,
                        ep=2, wire_dtype=wire,
                        wire_dtype_combine=wire, **F32)
        mesh = make_mesh(cfg, dp=1, devices=devices[:2])
        opt = make_optimizer(cfg, total_steps=50)
        state = init_state(jax.random.PRNGKey(0), cfg, opt)
        state = jax.device_put(state, state_shardings(state, cfg, mesh))
        step = make_train_step(cfg, mesh, opt)
        losses = []
        for i in range(50):
            batch = {"tokens": jax.random.randint(
                jax.random.PRNGKey(1000 + i), (2, cfg.sequence_len + 1),
                0, cfg.vocab_size)}
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    base = run(None)
    wired = run("bf16")
    assert all(np.isfinite(base)) and all(np.isfinite(wired))
    # training must actually progress, and the compressed run must track
    # the baseline: same trajectory within a few percent at the tail
    assert base[-1] < base[0]
    assert wired[-1] < wired[0]
    tail_b = np.mean(base[-10:])
    tail_w = np.mean(wired[-10:])
    assert abs(tail_w - tail_b) / abs(tail_b) < 0.05, (tail_b, tail_w)


# ----------------------------------------------------------------------
# Pricing + selection keys
# ----------------------------------------------------------------------

def test_comm_bytes_drop_by_itemsize_ratio():
    """analysis.path_costs: with compression on, the EP exchange bytes
    drop by the wire/compute itemsize ratio (exactly for bf16-on-f32;
    fp8 adds only the 4-byte-per-row scale sidecar)."""
    from flashmoe_tpu.analysis import path_costs, wire_row_bytes

    cfg = MoEConfig(num_experts=16, expert_top_k=2, hidden_size=256,
                    intermediate_size=512, sequence_len=2048,
                    capacity_factor=1.0, ep=8, **F32)
    for path in ("explicit", "ragged"):
        off = path_costs(cfg, path, d_world=8).comm_bytes
        bf = path_costs(cfg.replace(wire_dtype="bf16",
                                    wire_dtype_combine="bf16"),
                        path, d_world=8).comm_bytes
        assert off > 0
        assert bf == off / 2  # f32 -> bf16: exactly half
        fp8 = path_costs(cfg.replace(wire_dtype="e4m3",
                                     wire_dtype_combine="e4m3"),
                         path, d_world=8).comm_bytes
        # 4x on the payload; the f32 scale sidecar adds 4 bytes per
        # 256-byte fp8 row ~ 1.6%
        assert off / 4 < fp8 < off / 4 * 1.02
        # one leg compressed, one raw
        half = path_costs(cfg.replace(wire_dtype="bf16"),
                          path, d_world=8).comm_bytes
        assert half == off * 0.75
    # single-chip paths carry no exchange, compressed or not
    assert path_costs(cfg, "explicit", d_world=1).comm_bytes == 0.0
    assert wire_row_bytes(cfg) == cfg.hidden_size * 4
    with pytest.raises(ValueError, match="leg"):
        wire_row_bytes(cfg, "sideways")


def test_planner_prices_wire_and_excludes_fused():
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.planner.model import predict_paths

    ref = BENCH_CONFIGS["reference"]
    off = {p.path: p for p in predict_paths(ref, 8, "v5e")}
    on = {p.path: p for p in predict_paths(
        ref.replace(wire_dtype="e4m3"), 8, "v5e")}
    assert on["collective"].ici_ms < off["collective"].ici_ms
    assert on["collective"].total_ms < off["collective"].total_ms
    assert on["collective"].wire == "e4m3/off"
    assert off["collective"].wire == "off/off"
    for name, p in on.items():
        if name.startswith("fused"):
            assert not p.feasible
            assert "XLA-transport" in p.note
    # auto resolution with wire on lands on an XLA transport
    from flashmoe_tpu.planner.select import _cached_backend, \
        resolve_moe_backend

    _cached_backend.cache_clear()
    backend = resolve_moe_backend(
        ref.replace(moe_backend="auto", ep=8, wire_dtype="e4m3"))
    assert backend in ("collective", "ragged")
    _cached_backend.cache_clear()


def test_measured_latencies_keyed_by_wire(tmp_path, monkeypatch):
    """Satellite: a path latency measured with compression on is never
    applied to an uncompressed run, and vice versa — including legacy
    entries with no wire key (implicit off)."""
    import json

    from flashmoe_tpu import tuning
    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.planner.select import _cached_backend, select_path

    ref = BENCH_CONFIGS["reference"]
    shape = dict(h=ref.hidden_size, i=ref.intermediate_size, d=8)
    tbl = tmp_path / "table.json"
    tbl.write_text(json.dumps({"generation": "v5e", "entries": [
        {"kernel": "path_latency",
         "match": dict(shape, path="ragged", wire="e4m3"),
         "measured_ms": 0.0001},
        {"kernel": "path_latency",          # legacy: implicit wire=off
         "match": dict(shape, path="collective"),
         "measured_ms": 0.0002},
    ]}))
    monkeypatch.setenv("FLASHMOE_TUNING_FILE", str(tbl))
    monkeypatch.delenv("FLASHMOE_BENCH_RECORDS", raising=False)
    tuning._load.cache_clear()
    _cached_backend.cache_clear()
    try:
        # uncompressed query: only the legacy (off) entry applies
        off = tuning.measured_path_latencies("v5e", **shape)
        assert off == {"collective": 0.0002}
        # compressed query: only the e4m3 entry applies
        on = tuning.measured_path_latencies("v5e", **shape, wire="e4m3")
        assert on == {"ragged": 0.0001}
        # end to end through select_path: the measured winner follows
        # the config's wire knob
        sel_off = select_path(ref, 8, "v5e", record=False)
        assert (sel_off.mode, sel_off.winner) == ("measured", "collective")
        sel_on = select_path(ref.replace(wire_dtype="e4m3"), 8, "v5e",
                             record=False)
        assert (sel_on.mode, sel_on.winner) == ("measured", "ragged")
    finally:
        tuning._load.cache_clear()
        _cached_backend.cache_clear()


def test_bench_records_keyed_by_wire(tmp_path, monkeypatch):
    import json

    from flashmoe_tpu.config import BENCH_CONFIGS
    from flashmoe_tpu.planner.select import _bench_record_latencies

    ref = BENCH_CONFIGS["reference"]
    metric = (f"moe_layer_fwd_ms[x:E={ref.num_experts},"
              f"k={ref.expert_top_k},H={ref.hidden_size},"
              f"I={ref.intermediate_size},S={ref.tokens},bfloat16]")
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(
        {"metric": metric, "path": "collective", "value": 0.5, "d": 8,
         "wire_dtype": "e4m3"}) + "\n" + json.dumps(
        {"metric": metric, "path": "ragged", "value": 0.7, "d": 8}) + "\n")
    monkeypatch.setenv("FLASHMOE_BENCH_RECORDS", str(p))
    assert _bench_record_latencies(ref, 8) == {"ragged": 0.7}
    assert _bench_record_latencies(
        ref.replace(wire_dtype="e4m3"), 8) == {"collective": 0.5}
    assert _bench_record_latencies(
        ref.replace(wire_dtype="e5m2"), 8) == {}
